"""W3C-style trace context as plain data — deterministic, stdlib-only.

A trace context is two hex strings: a 128-bit ``trace_id`` naming one
request's end-to-end journey (router -> prefill -> KV handoff ->
decode) and a 64-bit ``span_id`` naming one unit of work inside it.
Spans form a tree via ``parent_id``; exactly one span per trace has no
parent (the root).

Everything here is **derived, never drawn**: ids come from sha256 over
``(seed, request id, role, ...)`` name parts, so a VirtualClock replay
of the same (seed, config) run produces byte-identical trace ids — the
property the deterministic-replay test pins.  No ``os.urandom``, no
clock, no global counter.

The context rides as *plain data* (three envelope fields ``trace`` /
``span`` / ``parent``, schema v2) on event records, ``Request``
objects, KV-handoff frame headers (inside ``meta``), router admission
records, fleet control-socket messages, and rendezvous RPC payloads.
Processes that receive a context re-emit it verbatim or derive child
spans from it; no process ever invents an unrelated id for work it did
on someone else's behalf.

Interop shape follows W3C Trace Context (``traceparent:
00-<trace>-<span>-01``) so external tooling can join these traces, but
propagation here is explicit-field, not header parsing.
"""

from __future__ import annotations

import dataclasses
import hashlib
import re

TRACE_ID_HEX = 32   # 128-bit
SPAN_ID_HEX = 16    # 64-bit

_TRACE_RE = re.compile(r"^[0-9a-f]{32}$")
_SPAN_RE = re.compile(r"^[0-9a-f]{16}$")

_TRACEPARENT_RE = re.compile(
    r"^00-([0-9a-f]{32})-([0-9a-f]{16})-[0-9a-f]{2}$"
)


def _digest(*parts) -> str:
    h = hashlib.sha256()
    for p in parts:
        h.update(str(p).encode())
        h.update(b"\x1f")  # unit separator: ("ab","c") != ("a","bc")
    return h.hexdigest()


def derive_trace_id(*parts) -> str:
    """128-bit hex trace id from name parts (typically (seed, rid))."""
    if not parts:
        raise ValueError("derive_trace_id needs at least one name part")
    return _digest("trace", *parts)[:TRACE_ID_HEX]


def derive_span_id(trace_id: str, *parts) -> str:
    """64-bit hex span id, scoped to ``trace_id`` by construction so
    equal role names in different traces never collide."""
    if not parts:
        raise ValueError("derive_span_id needs at least one name part")
    return _digest("span", trace_id, *parts)[:SPAN_ID_HEX]


def is_trace_id(value) -> bool:
    return isinstance(value, str) and bool(_TRACE_RE.match(value))


def is_span_id(value) -> bool:
    return isinstance(value, str) and bool(_SPAN_RE.match(value))


@dataclasses.dataclass(frozen=True)
class SpanContext:
    """One span's identity inside a trace.  Immutable; derive children
    with :meth:`child`, serialize with :meth:`to_fields`."""

    trace_id: str
    span_id: str
    parent_id: str | None = None

    def __post_init__(self):
        if not is_trace_id(self.trace_id):
            raise ValueError(f"bad trace_id {self.trace_id!r}")
        if not is_span_id(self.span_id):
            raise ValueError(f"bad span_id {self.span_id!r}")
        if self.parent_id is not None and not is_span_id(self.parent_id):
            raise ValueError(f"bad parent_id {self.parent_id!r}")

    def child(self, *parts) -> "SpanContext":
        """A child span named by ``parts`` (deterministic: same parent
        + same parts -> same child id)."""
        return SpanContext(
            trace_id=self.trace_id,
            span_id=derive_span_id(self.trace_id, self.span_id, *parts),
            parent_id=self.span_id,
        )

    def to_fields(self) -> dict:
        """The schema-v2 envelope fields this context contributes to an
        event record (or any JSON payload)."""
        out = {"trace": self.trace_id, "span": self.span_id}
        if self.parent_id is not None:
            out["parent"] = self.parent_id
        return out

    def traceparent(self) -> str:
        """W3C ``traceparent`` header form (version 00, sampled)."""
        return f"00-{self.trace_id}-{self.span_id}-01"


def root_context(*parts) -> SpanContext:
    """The root span of a new trace named by ``parts`` — trace id and
    root span id both derived from the same name, ``parent_id=None``."""
    trace_id = derive_trace_id(*parts)
    return SpanContext(
        trace_id=trace_id,
        span_id=derive_span_id(trace_id, "root"),
        parent_id=None,
    )


def from_fields(record) -> SpanContext | None:
    """Rebuild a context from a record/payload carrying ``trace`` /
    ``span`` (and optionally ``parent``) fields; None if absent or
    malformed — propagation is best-effort, never a crash."""
    if not isinstance(record, dict):
        return None
    trace, span = record.get("trace"), record.get("span")
    if not (is_trace_id(trace) and is_span_id(span)):
        return None
    parent = record.get("parent")
    if parent is not None and not is_span_id(parent):
        return None
    return SpanContext(trace_id=trace, span_id=span, parent_id=parent)


def from_traceparent(header: str) -> SpanContext | None:
    """Parse a W3C ``traceparent`` string; None on mismatch."""
    m = _TRACEPARENT_RE.match(header.strip()) \
        if isinstance(header, str) else None
    if not m:
        return None
    return SpanContext(trace_id=m.group(1), span_id=m.group(2))
