"""Gang timeline -> Chrome/Perfetto ``trace_event`` JSON.

The merged ``timeline.jsonl`` already totally orders every span and
telemetry event across the gang; this module re-expresses it in the
trace_event format so a whole run — every incarnation, every rank, the
supervisor's restart decisions — is inspectable in ``ui.perfetto.dev``
(or ``chrome://tracing``) next to XLA profiler captures.

Mapping:

* one *process* track per writer — supervisor is pid 0, rank ``r`` is
  pid ``r + 1`` — with ``process_name``/``thread_name`` metadata events;
* ``span`` events become ``"X"`` complete events.  The event log stamps
  a span at *exit* with its duration, so the trace start is
  ``ts - dur_s``; nesting is recovered by Perfetto from containment,
  which holds because spans on one writer are properly nested;
* counter tracks (``"C"``): ``step_s`` sampled from step spans, ``mfu``
  from mfu events, ``memory_bytes`` from memory events;
* discrete incidents — nan_skip, chaos_inject, watchdog_fire,
  restart_attempt / restart_exhausted, loader_starved, alert — become
  ``"i"`` instant events, so a restart is a visible mark on the
  supervisor track at the moment it happened;
* spans carrying schema-v2 trace context get a *per-trace thread
  track* (``req:<trace8>``) inside their process — concurrent requests
  stop rendering as one falsely-nested pile on tid 0 — and each
  multi-span trace is stitched across processes with legacy flow
  events (``"s"``/``"t"``/``"f"`` sharing the trace id), so clicking a
  request's root span in Perfetto draws arrows through its prefill →
  handoff → decode spans on whichever engines served it.

Timestamps are microseconds relative to the earliest instant in the
run (trace viewers want small numbers, not epoch µs).

Module-import rule: stdlib only (see schema.py) — ``scripts/
ddp_trace.py`` runs this in a jax-free interpreter.
"""

from __future__ import annotations

import json

#: kind -> counter-track name, value field
_COUNTER_KINDS = {
    "mfu": ("mfu", "mfu"),
    "memory": ("memory_bytes", "live_bytes"),
    # Serving: active decode slots over time — occupancy at a glance.
    "decode_step": ("active_slots", "n_active"),
    # Speculative decoding: accepted tokens per verify dispatch — the
    # accept-length track dropping toward n_active means drafts stopped
    # landing.
    "spec_verify": ("spec_accepted", "accepted"),
    # Serving fleet: bytes shipped per prefill->decode KV-block handoff
    # — spikes line up with prefill-tier completions on the span tracks.
    "kv_handoff": ("handoff_bytes", "bytes"),
}

#: kinds rendered as instant events (fields worth carrying into args)
_INSTANT_KINDS = {
    "nan_skip": ("step",),
    "chaos_inject": ("entry", "step"),
    "watchdog_fire": ("seconds_since_heartbeat",),
    "restart_attempt": ("attempt", "exit_code"),
    "restart_exhausted": ("attempt",),
    "loader_starved": ("window", "step"),
    "alert": ("rule", "step", "value", "threshold"),
    # Serving lifecycle marks (request spans come through "span"
    # records named "request:<rid>" and need no mapping here).
    "request_admit": ("req", "prompt_tokens", "slot", "queued_s"),
    "prefill_chunk": ("req", "start", "len"),
    "request_done": ("req", "ttft_s", "tokens", "latency_s"),
    "kv_evict": ("blocks", "req", "reason"),
    "prefix_hit": ("req", "tokens", "ctx"),
    # Serving fleet: routing decisions and engine-death verdicts.
    "route_admit": ("req", "engine", "prefill", "affinity", "session"),
    "engine_verdict": ("engine", "rung", "tier", "requeued", "reason"),
}

SUPERVISOR_PID = 0


def _pid(proc) -> int:
    """supervisor -> 0, rank r -> r + 1, unknown writers -> hash-free
    stable fallback pid 999 (keeps the trace loadable rather than
    raising on a foreign record)."""
    if proc == "supervisor":
        return SUPERVISOR_PID
    try:
        return int(proc) + 1
    except (TypeError, ValueError):
        return 999


def _track_name(proc) -> str:
    if proc == "supervisor":
        return "supervisor"
    try:
        return f"rank {int(proc)}"
    except (TypeError, ValueError):
        return str(proc)


def _span_start_s(rec: dict) -> float:
    return float(rec.get("ts", 0.0)) - float(rec.get("dur_s", 0.0) or 0.0)


def _args(rec: dict, fields) -> dict:
    out = {}
    for f in fields:
        if f in rec and rec[f] is not None:
            out[f] = rec[f]
    return out


def to_trace_events(records: list[dict]) -> dict:
    """Convert merged timeline records to a trace_event JSON object:
    ``{"traceEvents": [...], "displayTimeUnit": "ms"}``.  Pure host
    work over already-decoded records; ignores kinds it has no mapping
    for rather than failing on future schema additions."""
    # Epoch of the trace: the earliest instant anywhere, including span
    # starts (a span's exit ts may not be the first thing that happened).
    t0 = None
    for rec in records:
        ts = rec.get("ts")
        if not isinstance(ts, (int, float)):
            continue
        start = _span_start_s(rec) if rec.get("kind") == "span" else float(ts)
        t0 = start if t0 is None else min(t0, start)
    if t0 is None:
        return {"traceEvents": [], "displayTimeUnit": "ms"}

    def us(ts_s: float) -> float:
        return max(0.0, (ts_s - t0) * 1e6)

    events: list[dict] = []
    seen_pids: dict[int, str] = {}
    # Per-trace track + flow bookkeeping: trace id -> tid (tid 0 stays
    # the writer's "main" track), and trace id -> its span events in
    # append order (flow-stitched after the scan).
    trace_tids: dict[str, int] = {}
    flow_groups: dict[str, list[dict]] = {}
    for rec in records:
        proc = rec.get("proc")
        kind = rec.get("kind")
        ts = rec.get("ts")
        if kind is None or not isinstance(ts, (int, float)):
            continue
        pid = _pid(proc)
        seen_pids.setdefault(pid, _track_name(proc))

        if kind == "span":
            dur_s = rec.get("dur_s")
            if not isinstance(dur_s, (int, float)):
                continue
            trace_id = rec.get("trace")
            tid = 0
            if isinstance(trace_id, str) and trace_id:
                tid = trace_tids.setdefault(trace_id, len(trace_tids) + 1)
            ev = {
                "ph": "X",
                "name": str(rec.get("name", "span")),
                "cat": "span",
                "pid": pid,
                "tid": tid,
                "ts": us(_span_start_s(rec)),
                "dur": float(dur_s) * 1e6,
                "args": _args(
                    rec,
                    ("step", "epoch", "depth", "parent", "trace",
                     "span", "req", "engine"),
                ),
            }
            events.append(ev)
            if tid:
                flow_groups.setdefault(trace_id, []).append(ev)
            # step spans double as the step_s counter samples
            if rec.get("name") == "step":
                events.append({
                    "ph": "C",
                    "name": "step_s",
                    "pid": pid,
                    "tid": 0,
                    "ts": us(float(ts)),
                    "args": {"step_s": float(dur_s)},
                })
        elif kind in _COUNTER_KINDS:
            track, field = _COUNTER_KINDS[kind]
            value = rec.get(field)
            if isinstance(value, (int, float)):
                events.append({
                    "ph": "C",
                    "name": track,
                    "pid": pid,
                    "tid": 0,
                    "ts": us(float(ts)),
                    "args": {track: float(value)},
                })
        elif kind in _INSTANT_KINDS:
            events.append({
                "ph": "i",
                "name": kind,
                "cat": "incident",
                "pid": pid,
                "tid": 0,
                "ts": us(float(ts)),
                # supervisor incidents concern the whole gang
                "s": "g" if pid == SUPERVISOR_PID else "p",
                "args": _args(rec, _INSTANT_KINDS[kind]),
            })
            # route_admit carries the router's queue depth: double it
            # into a counter track (like step spans double as step_s).
            if kind == "route_admit" and isinstance(
                rec.get("queue_depth"), (int, float)
            ):
                events.append({
                    "ph": "C",
                    "name": "router_queue",
                    "pid": pid,
                    "tid": 0,
                    "ts": us(float(ts)),
                    "args": {
                        "router_queue": float(rec["queue_depth"])
                    },
                })

    # Flow stitching: each multi-span trace becomes one flow (legacy
    # "s"/"t"/"f" phases sharing the trace id), anchored at each span's
    # start on its own pid/tid — the cross-process arrow through a
    # request's prefill/handoff/decode hops.
    flows: list[dict] = []
    for trace_id, group in flow_groups.items():
        if len(group) < 2:
            continue
        group = sorted(group, key=lambda e: (e["ts"], e["ts"] + e["dur"]))
        for i, anchor in enumerate(group):
            ph = "s" if i == 0 else ("f" if i == len(group) - 1 else "t")
            fev = {
                "ph": ph,
                "name": f"req:{trace_id[:8]}",
                "cat": "trace",
                "id": trace_id[:16],
                "pid": anchor["pid"],
                "tid": anchor["tid"],
                "ts": anchor["ts"],
            }
            if ph == "f":
                fev["bp"] = "e"  # bind to the enclosing slice
            flows.append(fev)
    events.extend(flows)

    # Per-track monotonic order (viewers require ts-sorted streams per
    # track; a global ts sort gives that and keeps the file diffable).
    events.sort(key=lambda e: (e["ts"], e["pid"]))

    trace8_of_tid = {
        tid: f"req:{trace_id[:8]}" for trace_id, tid in trace_tids.items()
    }
    trace_threads = sorted({
        (e["pid"], e["tid"]) for e in events
        if e.get("ph") == "X" and e.get("tid")
    })
    meta: list[dict] = []
    for pid in sorted(seen_pids):
        meta.append({
            "ph": "M", "name": "process_name", "pid": pid, "tid": 0,
            "args": {"name": seen_pids[pid]},
        })
        meta.append({
            "ph": "M", "name": "process_sort_index", "pid": pid, "tid": 0,
            "args": {"sort_index": pid},
        })
        meta.append({
            "ph": "M", "name": "thread_name", "pid": pid, "tid": 0,
            "args": {"name": "main"},
        })
    for pid, tid in trace_threads:
        meta.append({
            "ph": "M", "name": "thread_name", "pid": pid, "tid": tid,
            "args": {"name": trace8_of_tid.get(tid, f"trace {tid}")},
        })
    return {"traceEvents": meta + events, "displayTimeUnit": "ms"}


def validate_trace(trace: dict) -> list[str]:
    """Structural check of a trace_event object (empty list = valid):
    required top-level shape, required per-event fields by phase,
    per-(pid, tid) monotonic timestamps, and flow integrity — every
    flow id must open with exactly one ``"s"``, close with a ``"f"``,
    and never continue (``"t"``/``"f"``) before it opened.  Used by
    tests and by ``ddp_trace.py --check`` before handing the file to a
    viewer."""
    problems = []
    if not isinstance(trace, dict) or "traceEvents" not in trace:
        return ["trace is not an object with a traceEvents array"]
    events = trace["traceEvents"]
    if not isinstance(events, list):
        return ["traceEvents is not an array"]
    last_ts: dict[tuple, float] = {}
    flows: dict[str, list[tuple[str, float]]] = {}  # id -> [(ph, ts)]
    for i, ev in enumerate(events):
        if not isinstance(ev, dict):
            problems.append(f"event {i}: not an object")
            continue
        ph = ev.get("ph")
        if ph not in ("X", "C", "i", "M", "s", "t", "f"):
            problems.append(f"event {i}: unsupported phase {ph!r}")
            continue
        for field in ("name", "pid", "tid"):
            if field not in ev:
                problems.append(f"event {i}: missing {field!r}")
        if ph == "M":
            continue
        ts = ev.get("ts")
        if not isinstance(ts, (int, float)) or ts < 0:
            problems.append(f"event {i}: bad ts {ts!r}")
            continue
        if ph == "X" and not isinstance(ev.get("dur"), (int, float)):
            problems.append(f"event {i}: complete event without dur")
        if ph == "i" and ev.get("s") not in ("g", "p", "t"):
            problems.append(f"event {i}: instant event bad scope {ev.get('s')!r}")
        if ph in ("s", "t", "f"):
            fid = ev.get("id")
            if not isinstance(fid, (str, int)):
                problems.append(f"event {i}: flow event without id")
                continue
            flows.setdefault(str(fid), []).append((ph, float(ts)))
        key = (ev.get("pid"), ev.get("tid"))
        if ts < last_ts.get(key, float("-inf")):
            problems.append(
                f"event {i}: ts {ts} regresses on track {key}"
            )
        last_ts[key] = float(ts)
    # Flow integrity, order-insensitive (same-ts events from different
    # pids interleave arbitrarily in the global sort): each id opens
    # exactly once, closes exactly once, and the open/close bracket
    # every step in time.
    for fid, phases in sorted(flows.items()):
        n_s = sum(1 for ph, _ in phases if ph == "s")
        n_f = sum(1 for ph, _ in phases if ph == "f")
        if n_s != 1 or n_f != 1:
            problems.append(
                f"flow {fid}: {n_s} start(s) / {n_f} finish(es), want "
                "exactly 1 of each — dangling flow id"
            )
            continue
        t_s = next(t for ph, t in phases if ph == "s")
        t_f = next(t for ph, t in phases if ph == "f")
        if any(not t_s <= t <= t_f for ph, t in phases if ph == "t"):
            problems.append(
                f"flow {fid}: step outside its start/finish window"
            )
    return problems


def write_trace(trace: dict, out_path: str) -> str:
    with open(out_path, "w") as fh:
        json.dump(trace, fh)
        fh.write("\n")
    return out_path
