"""Pipeline parallelism: GPipe-style stage sharding over a ``pipe`` mesh
axis (beyond-reference scope, completing the DP/TP/CP/PP axis set).

The TPU-native shape of PP exploits a property this framework already
has: with ``scan_layers=True`` the decoder stack's parameters are
STACKED arrays with a leading layer dimension, so "split the model into
stages" is literally "shard that leading dim over the pipe axis" — each
mesh position holds ``L / n_stages`` layers and runs the same scanned
block code on its slice.

Three schedules share the stage sharding (``make_pp_train_step(...,
schedule=)``): GPipe (default, below), 1F1B
(``_pp_1f1b_loss_and_grads`` — interleaved manual backward, O(stages)
activation memory instead of O(microbatches); see its docstring), and
``zb`` — a ZB-H1-style zero-bubble variant of 1F1B that splits each
backward into an activation-grad unit B (critical path) and a
weight-grad unit W, rendered as three segmented scans so warm-up ticks
never execute a dead backward slot and drain ticks never execute a
dead forward slot.  The per-stage useful-slot counters the schedules
carry make the bubble MEASURED (``pp_phase_counts`` in the step
metrics), not just analytic.

The GPipe schedule inside ``shard_map``:

- The per-position batch splits into M microbatches.  Each tick, stage 0
  injects the next microbatch's embeddings, every stage applies its
  layer slice, and activations rotate one hop with ``lax.ppermute``
  (XLA overlaps the transfer with the next tick's compute).
- After ``n_stages - 1`` warm-up ticks the pipe is full; the last stage
  computes logits + loss for one microbatch per tick.  Bubble ticks
  process don't-care buffers whose results never reach the loss, so AD
  gives them zero cotangents — and the BACKWARD pipeline (reverse
  schedule, reverse ppermute) emerges entirely from differentiating the
  forward loop; no hand-written reverse schedule exists anywhere.
- Replicated parameters (embeddings, final norm, lm head) get gradient
  contributions only on the stages that use them (0 and n-1); a psum
  over the pipe axis completes them.  Layer-slice gradients are local by
  construction.  The data axis then applies the ordinary DDP mean.

Restrictions: ``scan_layers=True`` configs without dropout.  DP, TP
(``cfg.tp_axis``), and CP (``cfg.cp_axis``, ring attention with
host-side input/target split) all compose with the pipeline; the
microbatch loop is itself the gradient-accumulation analog.
"""

from __future__ import annotations

from typing import Any

import flax.linen as nn
import jax
import jax.numpy as jnp
from jax import lax
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

Pytree = Any


def pp_param_specs(
    tree: Pytree,
    axis_name: str = "pipe",
    tp_axis: str | None = None,
    ep_axis: str | None = None,
) -> Pytree:
    """Spec tree: any leaf under a ``layers`` path component shards its
    LEADING (stacked-layer) dim over the pipe axis; everything else is
    replicated.  Works for optimizer state too (optax trees embed the
    param paths).

    With ``tp_axis``/``ep_axis`` the Megatron / expert trailing-dim rules
    compose underneath (disjoint leaf sets): a stacked q_proj kernel
    becomes e.g. ``P('pipe', None, 'model', None)``, a stacked expert
    weight ``P('pipe', 'expert', None, None)``.
    """
    from distributeddataparallel_tpu.parallel import (
        expert_parallel,
        tensor_parallel,
    )

    flat = jax.tree_util.tree_flatten_with_path(tree)[0]
    treedef = jax.tree.structure(tree)
    specs = []
    for path, leaf in flat:
        names = tuple(
            str(getattr(k, "key", getattr(k, "name", k))) for k in path
        )
        if "layers" in names and getattr(leaf, "ndim", 0) >= 1:
            trailing = (None,) * (leaf.ndim - 1)
            for axis, rule in (
                (tp_axis, tensor_parallel._spec_for_path),
                (ep_axis, expert_parallel._spec_for_path),
            ):
                if axis is None:
                    continue
                inner = rule(names, leaf, axis)
                if any(inner):
                    # Right-aligned partition of the trailing dims (the
                    # leading dim is the stacked layer axis).
                    trailing = tuple(inner)[-(leaf.ndim - 1):]
                    break
            specs.append(P(*((axis_name,) + trailing)))
        else:
            specs.append(P())
    return jax.tree.unflatten(treedef, specs)


def pp_state_specs(
    state,
    axis_name: str = "pipe",
    tp_axis: str | None = None,
    ep_axis: str | None = None,
) -> Pytree:
    """Spec tree for a whole TrainState under PP (single source for both
    placement and the step's shard_map in_specs)."""
    return state.replace(
        step=P(),
        params=pp_param_specs(state.params, axis_name, tp_axis, ep_axis),
        opt_state=pp_param_specs(state.opt_state, axis_name, tp_axis, ep_axis),
        model_state=jax.tree.map(lambda _: P(), state.model_state),
    )


def interleave_layer_perm(L: int, n: int, v: int) -> "np.ndarray":
    """Storage order of the stacked layer dim for interleaved 1F1B.

    With ``v`` virtual chunks per stage (Megatron-LM interleaved
    schedule, arXiv 2104.04473 §2.3), stage ``s`` owns the round-robin
    layer chunks ``{s, s+n, ..., s+(v-1)n}`` (chunk length
    ``Lc = L/(n·v)``) — non-contiguous in logical layer order.  The
    stacked dim shards CONTIGUOUSLY over the pipe axis, so placement
    permutes rows so that position ``s``'s contiguous block is its v
    chunks in chunk-major order.  Returns ``perm`` with
    ``stored = logical[perm]``; invert with ``np.argsort(perm)``.
    """
    import numpy as np

    Lc = L // (n * v)
    perm = np.empty((L,), np.int64)
    i = 0
    for s in range(n):
        for c in range(v):
            base = (c * n + s) * Lc
            perm[i : i + Lc] = np.arange(base, base + Lc)
            i += Lc
    return perm


def shard_state_pp(
    state,
    mesh: Mesh,
    axis_name: str = "pipe",
    tp_axis: str | None = None,
    ep_axis: str | None = None,
    virtual: int = 1,
):
    """Place a full TrainState with the stacked layer dim sharded over the
    pipe axis (the PP analog of ``broadcast_params``).

    ``virtual > 1`` (interleaved 1F1B): the stacked layer dim of every
    ``layers`` leaf — params AND optimizer state (optax trees embed the
    param paths) — is stored in ``interleave_layer_perm`` order before
    placement, so each pipe position's contiguous shard is its v
    round-robin chunks.  Invert with the perm's argsort when gathering
    params back to the logical model layout.
    """
    import numpy as np

    n = mesh.shape[axis_name]
    for path, leaf in jax.tree_util.tree_flatten_with_path(state.params)[0]:
        names = tuple(str(getattr(k, "key", k)) for k in path)
        if "layers" in names and leaf.shape[0] % (n * virtual):
            raise ValueError(
                f"pipeline: stacked layer dim {leaf.shape[0]} of param "
                f"{'/'.join(names)} is not divisible by {n} stages"
                + (f" x {virtual} virtual chunks" if virtual > 1 else "")
            )
    if virtual > 1:
        def permute_layers(tree):
            flat = jax.tree_util.tree_flatten_with_path(tree)
            out = []
            for path, leaf in flat[0]:
                names = tuple(str(getattr(k, "key", k)) for k in path)
                if "layers" in names and getattr(leaf, "ndim", 0) >= 1:
                    perm = interleave_layer_perm(leaf.shape[0], n, virtual)
                    leaf = jnp.asarray(leaf)[np.asarray(perm)]
                out.append(leaf)
            return jax.tree.unflatten(flat[1], out)

        state = state.replace(
            params=permute_layers(state.params),
            opt_state=permute_layers(state.opt_state),
        )
    if ep_axis is not None:
        from distributeddataparallel_tpu.parallel.expert_parallel import (
            check_ep_divisibility,
        )

        check_ep_divisibility(state.params, mesh, ep_axis)
    return jax.tree.map(
        lambda x, s: jax.device_put(x, NamedSharding(mesh, s)),
        state,
        pp_state_specs(state, axis_name, tp_axis, ep_axis),
    )


def _stage_stack(cfg, n_stages: int):
    """The scanned block module for ONE stage's layer slice — built by
    the same factory TransformerLM uses (``scanned_layer_cls``), so a
    slice of the full model's stacked params applies directly and the
    two can never drift."""
    from distributeddataparallel_tpu.models.transformer import (
        scanned_layer_cls,
    )

    if cfg.num_layers % n_stages:
        raise ValueError(
            f"pipeline: num_layers {cfg.num_layers} not divisible by "
            f"{n_stages} stages"
        )
    return scanned_layer_cls(cfg, cfg.num_layers // n_stages)(cfg)


def _embed(cfg, params, tokens, positions=None):
    """Token (+ learned positional) embedding from raw params — mirrors
    TransformerLM's input block (models/transformer.py) without dropout.

    ``positions``: global token positions of this shard (context
    parallelism); defaults to ``arange(S)``.
    """
    emb = params["token_embed"]["embedding"]  # (V, d) f32
    x = emb[tokens].astype(cfg.dtype)
    if cfg.positional == "learned":
        if positions is None:
            # Static slice (cheaper than a gather-by-iota in the tick loop).
            pos = params["pos_embed"][: tokens.shape[1]]
        else:
            pos = params["pos_embed"][positions]
        x = x + pos.astype(cfg.dtype)
    return x


def _head(cfg, params, x):
    """Final norm + logits from raw params — mirrors TransformerLM's
    output block (f32 logits, cfg.dtype matmul operands)."""
    from distributeddataparallel_tpu.models.transformer import _make_norm

    x = _make_norm(cfg, "final_norm").apply(
        {"params": params["final_norm"]}, x
    )
    if cfg.tie_embeddings:
        w = params["token_embed"]["embedding"].astype(cfg.dtype)  # (V, d)
        return jax.lax.dot_general(
            x.astype(cfg.dtype), w, (((x.ndim - 1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32,
        )
    w = params["lm_head"]["kernel"].astype(cfg.dtype)  # (d, V)
    return jax.lax.dot_general(
        x.astype(cfg.dtype), w, (((x.ndim - 1,), (0,)), ((), ())),
        preferred_element_type=jnp.float32,
    )


def _check_seq_bound(cfg, S: int, n_cp: int = 1) -> None:
    """Same guard TransformerLM.__call__ enforces: past the positional
    table bound, XLA silently CLAMPS RoPE/pos_embed gathers instead of
    erroring — training/eval would proceed on wrong positions."""
    if S * n_cp > cfg.max_seq_len:
        raise ValueError(
            f"global seq len {S * n_cp} > max_seq_len {cfg.max_seq_len}"
        )


def _pipeline_ticks(
    cfg,
    params,
    mbs_in,
    *,
    pp_axis: str,
    n: int,
    microbatches: int,
    run_stage,
    on_output,
    positions=None,
):
    """THE GPipe schedule, shared by the train and eval steps: M + n - 1
    ticks; each tick embeds the next microbatch at stage 0, applies this
    stage's layer slice (``run_stage(x, t, s) -> y``), rotates activations
    one hop, and hands each completed microbatch's last-stage activations
    to ``on_output(mb_index, y, s)``.  Callers accumulate through
    closures; bubble outputs are don't-care values the callers mask on
    ``s == n - 1``, which is what lets AD reconstruct the reverse
    pipeline on its own.
    """
    M = microbatches
    s = lax.axis_index(pp_axis)
    _, mb_rows, S = mbs_in.shape
    perm = [(i, (i + 1) % n) for i in range(n)]
    buf = jnp.zeros((mb_rows, S, cfg.d_model), cfg.dtype)
    for t in range(M + n - 1):
        x0 = _embed(cfg, params, mbs_in[min(t, M - 1)], positions)
        x = jnp.where(s == 0, x0, buf)
        y = run_stage(x, t, s)
        buf = lax.ppermute(y, pp_axis, perm)
        oi = t - (n - 1)
        if oi >= 0:
            on_output(oi, y, s)


def make_pp_eval_step(
    cfg,
    *,
    mesh: Mesh,
    microbatches: int,
    data_axis: str = "data",
    pp_axis: str = "pipe",
):
    """Forward-only pipelined evaluation for a scanned TransformerLM.

    ``eval_step(params, batch) -> (metrics, count)`` with the same
    contract as ``make_eval_step(masked=True)``: ``batch = {"tokens":
    (B_local, S+1), "valid": (B_local,)}`` sharded over ``data_axis``,
    per-row metrics weighted by the valid mask so sampler-padded
    duplicate rows contribute nothing, and the returned count is the
    global number of valid rows.  The microbatch ticks reuse the same
    embed/stack/head pieces as the train pipeline; only the last stage's
    outputs reach the metric sums (masked per position, completed with
    one psum over the pipe).  TP composes exactly as in training.
    """
    from distributeddataparallel_tpu.models.transformer import (
        rope_frequencies,
    )
    from distributeddataparallel_tpu.ops.losses import (
        per_example_accuracy,
        per_example_cross_entropy,
    )

    if not cfg.scan_layers:
        raise ValueError("pipeline parallelism requires scan_layers=True")
    if cfg.cp_axis is not None:
        raise ValueError("pipelined eval does not support cp_axis")
    n_stages = mesh.shape[pp_axis]
    M = microbatches
    stack = _stage_stack(cfg, n_stages)

    def _eval(params, batch):
        toks, valid = batch["tokens"], batch["valid"]
        inputs, targets = toks[:, :-1], toks[:, 1:]
        n = n_stages
        pad = (-inputs.shape[0]) % M
        if pad:
            # Tail batch whose per-position rows don't divide the
            # microbatch count (drop_last=False loaders): pad with
            # valid=0 rows — the mask already zero-weights them, same
            # contract as the non-PP masked eval.
            zrow = jnp.zeros((pad, inputs.shape[1]), inputs.dtype)
            inputs = jnp.concatenate([inputs, zrow])
            targets = jnp.concatenate([targets, zrow])
            valid = jnp.concatenate(
                [valid, jnp.zeros((pad,), valid.dtype)]
            )
        mb_rows = inputs.shape[0] // M
        S = inputs.shape[1]
        _check_seq_bound(cfg, S)
        mbs_in = inputs.reshape(M, mb_rows, S)
        mbs_tgt = targets.reshape(M, mb_rows, S)
        mbs_val = valid.reshape(M, mb_rows).astype(jnp.float32)
        rope = (
            rope_frequencies(
                cfg.dims_per_head, cfg.max_seq_len, theta=cfg.rope_theta
            )
            if cfg.positional == "rope"
            else None
        )
        layer_shard = params["layers"]
        loss_sum = acc_sum = cnt = jnp.zeros((), jnp.float32)

        def run_stage(x, t, s):
            y, _ = stack.apply({"params": layer_shard}, x, None, rope, True)
            return y

        def on_output(oi, y, s):
            nonlocal loss_sum, acc_sum, cnt
            logits = _head(cfg, params, y)
            v = mbs_val[oi]
            on_last = (s == n - 1).astype(jnp.float32)
            loss_sum = loss_sum + on_last * jnp.sum(
                per_example_cross_entropy(logits, mbs_tgt[oi]) * v
            )
            acc_sum = acc_sum + on_last * jnp.sum(
                per_example_accuracy(logits, mbs_tgt[oi]) * v
            )
            cnt = cnt + on_last * jnp.sum(v)

        _pipeline_ticks(
            cfg, params, mbs_in, pp_axis=pp_axis, n=n, microbatches=M,
            run_stage=run_stage, on_output=on_output,
        )
        # Only stage n-1 accumulated: the pipe psum replicates the sums;
        # the data psum then makes them global.
        sums = [
            lax.psum(lax.psum(x, pp_axis), data_axis)
            for x in (loss_sum, acc_sum, cnt)
        ]
        loss_sum, acc_sum, cnt = sums
        denom = jnp.maximum(cnt, 1.0)
        return {"loss": loss_sum / denom, "accuracy": acc_sum / denom}, cnt

    compiled = None

    def eval_step(params, batch):
        nonlocal compiled
        if compiled is None:
            pspecs = pp_param_specs(params, pp_axis, cfg.tp_axis, cfg.ep_axis)
            sharded = jax.shard_map(
                _eval,
                mesh=mesh,
                in_specs=(
                    pspecs,
                    {"tokens": P(data_axis), "valid": P(data_axis)},
                ),
                out_specs=(P(), P()),
                check_vma=False,
            )
            compiled = jax.jit(sharded)
        return compiled(params, batch)

    return eval_step


def _pp_1f1b_loss_and_grads(
    cfg,
    stack,
    params,
    inputs,
    targets,
    *,
    pp_axis: str,
    n: int,
    microbatches: int,
    moe_aux_weight: float = 0.0,
    virtual: int = 1,
    schedule: str = "1f1b",
):
    """1F1B schedule with a MANUAL backward: returns ``(loss, grads,
    phase_counts)`` with the (loss, grads) pair shaped exactly like
    ``value_and_grad(pp_loss)`` so the surrounding step (pipe psum
    completion, DP sync, ZeRO) is schedule-agnostic.  ``phase_counts``
    is a per-stage ``(3,)`` int32 vector counting the VALID (F, B, W)
    slots this stage executed — the measured side of the bubble
    accounting (off-schedule masked slots don't count).

    GPipe (``pp_loss``) differentiates through the whole tick loop, so
    AD keeps every microbatch's stage activations alive until the
    reverse sweep — O(M) activation memory.  Here forward and backward
    interleave on a synchronized alternating clock (even ticks forward,
    odd ticks backward — the SPMD rendering of Megatron-LM's 1F1B,
    arXiv 2104.04473 §2.2): a microbatch's backward starts as soon as
    its forward leaves the last stage, so at most ``2(n-1)`` microbatch
    inputs are in flight per stage regardless of M.  Only the STAGE
    INPUT is saved per in-flight microbatch (a ``2n+1``-slot ring, last
    slot = scratch for masked writes); the backward tick recomputes the
    stage forward under ``jax.vjp`` — stage-granular activation
    checkpointing, the standard 1F1B memory/compute trade.

    Schedule (F-tick index k, B-tick index k'): stage s runs forward of
    microbatch ``k - s`` and backward of microbatch ``k' - 2(n-1) + s``;
    activations hop +1 after every F-tick, cotangents hop -1 after every
    B-tick.  The last stage seeds the backward from the loss vjp of the
    microbatch it just finished; stage 0's outgoing cotangent feeds the
    embedding vjp.  Per-stage schedule shifts are data-dependent on
    ``axis_index``, so off-schedule ticks compute on clamped dummies and
    every accumulation is masked — exactly the trick the GPipe path uses
    for its bubble ticks.

    MoE aux loss (``moe_aux_weight > 0``): the B-tick's ``jax.vjp`` of
    the stage already recomputes the stage forward, so the router's
    sown aux value rides along free — the stage function returns
    ``(y, aux)`` (``mutable=["intermediates"]`` inside the vjp) and the
    aux output's cotangent is the constant ``moe_aux_weight/(n·M)``,
    matching GPipe's ``psum(aux_acc)/(n·M)`` term exactly.

    TP and CP compose: the stage body's Megatron psums and the ring's
    ppermutes sit inside ``jax.vjp``, which transposes them exactly as
    AD does; the outer step completes the sequence-sharded gradient
    with its cp pmean, schedule-agnostic.

    Head/embed vjps are gated on the owning stage with ``lax.cond``
    (ADVICE r3): at Llama-scale vocab the d×V head matmuls rival a
    stage's layer compute, so running them masked-to-zero on every
    stage would cost ~n_stages× redundant FLOPs per tick.  The
    predicate depends only on the pipe index, so model-axis peers
    always agree — any Megatron collective inside the branch stays
    matched.

    ``virtual > 1`` — INTERLEAVED 1F1B (Megatron arXiv 2104.04473
    §2.3): each stage holds ``v`` round-robin layer chunks (state
    placed with ``shard_state_pp(virtual=v)``; ``stack`` is built for
    chunk length ``L/(n·v)``) and the schedule's unit becomes a
    (chunk, microbatch) pair.  Microbatches proceed in groups of n;
    within a group, stage s's F-unit sequence is chunk-major
    ``(c, m mod n)`` and its B-unit sequence is reverse-chunk-major —
    the generalization keeps every transfer a +1 (F) / -1 (B) ring hop
    with one tick of latency, including the wrap that carries chunk c's
    output from stage n-1 into chunk c+1 on stage 0, so the alternating
    F/B clock and masked-validity machinery are unchanged.  Fill/drain
    spans become ``v·n`` chunk-ticks of 1/v stage-work each, shrinking
    the warm-up/drain bubble per device from ``(n-1)`` stage-units
    toward ``n/2 + n/(2v)`` — the measured tick accounting is reported
    by ``pp_bubble_fraction`` and recorded in the bench.  Requires
    ``num_layers % (n·v) == 0``; the unit ordering needs no divisibility
    of M (off-group units are masked like any bubble tick).

    ``schedule="zb"`` — ZERO-BUBBLE (ZB-H1-style W/B decomposition,
    arXiv 2401.10241 lineage; see also arXiv 2412.14374): the joint
    stage vjp splits into an activation-grad unit **B** (``jax.vjp``
    w.r.t. the stage input only — the cotangent must keep flowing up
    the pipe, so B stays on the critical path) and a weight-grad unit
    **W** (``jax.vjp`` w.r.t. the layer params only — nothing
    downstream consumes it, so it is off the critical path).  XLA CSE
    merges the two vjps' duplicated forward recompute, and each
    primitive's transpose is evaluated identically in both renderings,
    so dx/dW are bit-identical to the joint vjp's.

    In this SPMD masked-scan rendering a masked slot still burns wall
    clock, so the win comes from SEGMENTATION, not from moving W: the
    1F1B scan executes an F-slot AND a B-slot every tick (2T slots of
    capacity for 2Mv useful), while the zb rendering runs three scans
    with heterogeneous bodies — warm-up ticks ``[0, vn-1)`` execute
    only the F slot, steady ticks ``[vn-1, j_last+n)`` execute F+B+W,
    drain ticks ``[j_last+n, T)`` execute only B+W — so the dead
    phases genuinely do not execute.  Capacity drops to
    ``3·(j_last+n)`` slots for ``3Mv`` useful: bubble
    ``1 - Mv/(j_last+n)`` vs 1F1B's ``1 - Mv/T``
    (``_zb_segments`` / ``pp_bubble_fraction(schedule="zb")``).  W
    runs the SAME tick as its B (deferral depth 0): deferring W
    further would lengthen the scan without creating capacity, and
    same-tick W keeps memory identical to 1F1B — the activation ring
    is unchanged and no pending-W state accumulates.  Composition
    limits in v1: no ``cfg.cp_axis`` and no MoE aux loss (the factory
    rejects both loudly); TP and ZeRO compose as in 1F1B.
    """
    from distributeddataparallel_tpu.models.transformer import (
        rope_frequencies,
    )
    from distributeddataparallel_tpu.ops.losses import lm_cross_entropy

    M = microbatches
    s = lax.axis_index(pp_axis)
    mb_rows = inputs.shape[0] // M
    S = inputs.shape[1]
    positions = None
    n_cp = 1
    if cfg.cp_axis is not None:
        # CP composition: inputs arrive sequence-sharded (host-side
        # shift, see shard_lm_batch); the stage blocks run ring
        # attention with global positions.  The ring's ppermutes sit
        # inside jax.vjp, which transposes them exactly as AD does (the
        # same argument as TP) — and the outer _step completes the
        # seq-sharded gradient with its cp pmean, schedule-agnostic.
        from distributeddataparallel_tpu.parallel.context_parallel import (
            cp_positions,
        )

        n_cp = int(lax.psum(1, cfg.cp_axis))
        positions = cp_positions(S, cfg.cp_axis)
    _check_seq_bound(cfg, S, n_cp)
    mbs_in = inputs.reshape(M, mb_rows, S)
    mbs_tgt = targets.reshape(M, mb_rows, S)
    rope = (
        rope_frequencies(
            cfg.dims_per_head, cfg.max_seq_len, theta=cfg.rope_theta
        )
        if cfg.positional == "rope"
        else None
    )

    head_keys = ("final_norm",) + (
        ("token_embed",) if cfg.tie_embeddings else ("lm_head",)
    )
    embed_keys = ("token_embed",) + (
        ("pos_embed",) if cfg.positional == "learned" else ()
    )

    use_aux = cfg.moe_experts > 0 and moe_aux_weight > 0.0

    def stage_fn(layer_params, x):
        y, _ = stack.apply(
            {"params": layer_params}, x, positions, rope, True
        )
        return y

    def stage_fn_aux(layer_params, x):
        from distributeddataparallel_tpu.models.transformer import (
            moe_aux_from_intermediates,
        )

        (y, _), col = stack.apply(
            {"params": layer_params}, x, positions, rope, True,
            mutable=["intermediates"],
        )
        return y, moe_aux_from_intermediates(col)

    def head_loss(hparams, y, tgt):
        return lm_cross_entropy(_head(cfg, hparams, y), tgt)

    def embed_fn(eparams, toks):
        return _embed(cfg, eparams, toks, positions)

    v = virtual
    # Chunk length of the LOCAL stacked shard (leaves carry L/n rows;
    # each of the v chunks is L/(n*v) of them).
    n_slots = v * 2 * n + 1      # per-chunk 2n ring; last slot = scratch
    saved = jnp.zeros((n_slots, mb_rows, S, cfg.d_model), cfg.dtype)
    fbuf = jnp.zeros((mb_rows, S, cfg.d_model), cfg.dtype)
    bbuf = jnp.zeros((mb_rows, S, cfg.d_model), cfg.dtype)
    gacc = jax.tree.map(jnp.zeros_like, params)
    loss_acc = jnp.zeros((), jnp.float32)
    perm_f = [(i, (i + 1) % n) for i in range(n)]
    perm_b = [((i + 1) % n, i) for i in range(n)]

    def _acc(acc_tree, keys, grad_tree, w):
        out = dict(acc_tree)
        for k in keys:
            out[k] = jax.tree.map(
                lambda a, g: a + g.astype(a.dtype) * w,
                acc_tree[k], grad_tree[k],
            )
        return out

    def _decode_unit(j):
        """Unit index -> (chunk, microbatch, valid): groups of n
        microbatches cycle chunk-major (g asc, c asc, m-offset asc)."""
        g = j // (n * v)
        r = j % (n * v)
        c = r // n
        m = g * n + (r % n)
        valid = (j >= 0) & (m < M)
        return c, m, valid

    def _chunk_params(c):
        if v == 1:
            return params["layers"]
        Lc = jax.tree.leaves(params["layers"])[0].shape[0] // v
        return jax.tree.map(
            lambda p: lax.dynamic_slice_in_dim(p, c * Lc, Lc, 0),
            params["layers"],
        )

    _, T = _1f1b_ticks(n, M, v)
    split_bw = schedule == "zb"
    if split_bw and use_aux:
        raise ValueError("zb schedule does not support the MoE aux loss")

    # lax.scan, NOT an unrolled python loop, for two load-bearing
    # reasons: the carried ring buffer updates alias in place, and
    # iteration boundaries stop the scheduler from hoisting every
    # B-tick's recompute ahead of the backwards (which would resurrect
    # the O(M) liveness this schedule exists to kill).  The tick body
    # is factored into per-phase SLOTS so 1f1b (F+B every tick) and zb
    # (segmented F / F+B+W / B+W bodies) render from the same code.
    def f_slot(carry, i):
        # --- F slot, tick i: stage s runs forward of unit i - s --------
        # (0 <= m < M subsumes the tick-range bound: i < T implies the
        # per-stage unit index is already past the last unit when
        # off-schedule)
        saved, fbuf, bbuf, gacc, loss_acc, aux_acc, counts = carry
        cf, mf, valid = _decode_unit(i - s)
        mc = jnp.clip(mf, 0, M - 1)
        toks = lax.dynamic_index_in_dim(mbs_in, mc, 0, keepdims=False)
        x = jnp.where((s == 0) & (cf == 0), embed_fn(params, toks), fbuf)
        slot = jnp.where(valid, cf * (2 * n) + mc % (2 * n), v * 2 * n)
        saved = lax.dynamic_update_slice_in_dim(saved, x[None], slot, 0)
        fbuf = lax.ppermute(stage_fn(_chunk_params(cf), x), pp_axis, perm_f)
        counts = counts + valid.astype(jnp.int32) * jnp.array(
            [1, 0, 0], jnp.int32
        )
        return (saved, fbuf, bbuf, gacc, loss_acc, aux_acc, counts)

    def bw_slot(carry, i):
        # --- B (+W) slot, tick i: stage s runs backward of unit
        #     i - (vn - 1) - (n - 1 - s), chunks in REVERSE order -------
        saved, fbuf, bbuf, gacc, loss_acc, aux_acc, counts = carry
        cb, mb_, valid = _decode_unit(i - (v * n - 1) - (n - 1 - s))
        cb = v - 1 - cb
        mc = jnp.clip(mb_, 0, M - 1)
        slot = jnp.where(valid, cb * (2 * n) + mc % (2 * n), v * 2 * n)
        xb = lax.dynamic_index_in_dim(saved, slot, 0, keepdims=False)
        chunk_p = _chunk_params(cb)
        if split_bw:
            # ZB W/B decomposition: B = vjp w.r.t. the stage INPUT only
            # (params enter as a closure constant, so no dW cotangent
            # path is built); W below is the params-only twin.
            y, b_vjp = jax.vjp(lambda xx: stage_fn(chunk_p, xx), xb)
            aux = jnp.zeros((), jnp.float32)
        elif use_aux:
            (y, aux), stage_vjp = jax.vjp(stage_fn_aux, chunk_p, xb)
        else:
            y, stage_vjp = jax.vjp(stage_fn, chunk_p, xb)
            aux = jnp.zeros((), jnp.float32)
        tgt = lax.dynamic_index_in_dim(mbs_tgt, mc, 0, keepdims=False)
        on_last = (s == n - 1) & (cb == v - 1)
        head_params = {kk: params[kk] for kk in head_keys}

        # Gated head vjp (ADVICE r3): only the last stage pays the d×V
        # matmuls; other stages take the zeros branch.  The predicate is
        # uniform across non-pipe axes, so branch collectives match.
        def do_head(y_):
            lval, head_vjp = jax.vjp(
                lambda hp, yy: head_loss(hp, yy, tgt), head_params, y_
            )
            # Seed 1/M: the step's loss is the microbatch MEAN, so each
            # microbatch's cotangent carries the mean's scaling.
            dhp_, dy_ = head_vjp(jnp.full((), 1.0 / M, lval.dtype))
            return lval, dhp_, dy_

        def skip_head(y_):
            return jax.tree.map(
                lambda t: jnp.zeros(t.shape, t.dtype),
                jax.eval_shape(do_head, y_),
            )

        lval, dhp, dy_head = lax.cond(on_last, do_head, skip_head, y)
        gy = jnp.where(on_last, dy_head.astype(fbuf.dtype), bbuf)
        if split_bw:
            # B unit: activation grad only — the cotangent the next
            # stage up is waiting on.  W unit: weight grad only, same
            # tick (deferral depth 0 — see the docstring).  Each vjp
            # transposes the same primitives the joint vjp would, so
            # dx/dlayers are bit-identical and CSE shares the recompute.
            (dx,) = b_vjp(gy)
            _, w_vjp = jax.vjp(lambda lp: stage_fn(lp, xb), chunk_p)
            (dlayers,) = w_vjp(gy)
        elif use_aux:
            # The aux output's cotangent: GPipe adds
            # moe_aux_weight * psum(aux_acc) / (n*M) to the loss, so
            # every valid (stage-chunk, microbatch) aux value carries
            # this constant derivative (v·n·M units in total).  Invalid
            # ticks are masked by w below.
            dlayers, dx = stage_vjp(
                (gy, jnp.asarray(moe_aux_weight / (n * v * M), aux.dtype))
            )
        else:
            dlayers, dx = stage_vjp(gy)
        toksb = lax.dynamic_index_in_dim(mbs_in, mc, 0, keepdims=False)

        # Gated embed vjp: only stage 0's outgoing cotangent feeds it.
        def do_embed(dx_):
            _, embed_vjp = jax.vjp(
                lambda ep: embed_fn(ep, toksb),
                {kk: params[kk] for kk in embed_keys},
            )
            (dep_,) = embed_vjp(dx_)
            return dep_

        def skip_embed(dx_):
            return jax.tree.map(
                lambda t: jnp.zeros(t.shape, t.dtype),
                jax.eval_shape(do_embed, dx_),
            )

        dep = lax.cond((s == 0) & (cb == 0), do_embed, skip_embed, dx)
        w = valid.astype(jnp.float32)
        if v == 1:
            gacc = _acc(gacc, ("layers",), {"layers": dlayers}, w)
        else:
            Lc = jax.tree.leaves(params["layers"])[0].shape[0] // v

            def _upd_chunk(a, g):
                cur = lax.dynamic_slice_in_dim(a, cb * Lc, Lc, 0)
                return lax.dynamic_update_slice_in_dim(
                    a, cur + g.astype(a.dtype) * w, cb * Lc, 0
                )

            gacc = {
                **gacc,
                "layers": jax.tree.map(_upd_chunk, gacc["layers"], dlayers),
            }
        gacc = _acc(gacc, head_keys, dhp, w * on_last.astype(jnp.float32))
        gacc = _acc(gacc, embed_keys, dep, w)
        loss_acc = loss_acc + jnp.where(valid & on_last, lval, 0.0)
        aux_acc = aux_acc + jnp.where(valid, aux, 0.0)
        bbuf = lax.ppermute(dx, pp_axis, perm_b)
        counts = counts + valid.astype(jnp.int32) * (
            jnp.array([0, 1, 1], jnp.int32) if split_bw
            else jnp.array([0, 1, 0], jnp.int32)
        )
        return (saved, fbuf, bbuf, gacc, loss_acc, aux_acc, counts)

    aux_acc = jnp.zeros((), jnp.float32)
    counts = jnp.zeros((3,), jnp.int32)
    carry = (saved, fbuf, bbuf, gacc, loss_acc, aux_acc, counts)
    if split_bw:
        # Three segmented scans with heterogeneous bodies — THE
        # zero-bubble mechanism (the W split alone buys nothing in an
        # SPMD rendering where masked slots still burn wall clock):
        # warm-up ticks run no backward slot, drain ticks run no
        # forward slot, so per-stage capacity is 3·(j_last+n) slots
        # instead of the uniform body's 3·T.  Tick indices stay GLOBAL
        # across the segments; the arithmetic is _zb_segments — the
        # same closed form pp_bubble_fraction(schedule="zb") prices.
        warm, _steady, _drain, f_end = _zb_segments(n, M, v)

        def f_tick(c, i):
            return f_slot(c, i), None

        def fbw_tick(c, i):
            return bw_slot(f_slot(c, i), i), None

        def bw_tick(c, i):
            return bw_slot(c, i), None

        carry, _ = lax.scan(
            f_tick, carry, jnp.arange(0, warm, dtype=jnp.int32)
        )
        carry, _ = lax.scan(
            fbw_tick, carry, jnp.arange(warm, f_end, dtype=jnp.int32)
        )
        carry, _ = lax.scan(
            bw_tick, carry, jnp.arange(f_end, T, dtype=jnp.int32)
        )
    else:
        # One scan iteration = one F-tick + one B-tick (the even/odd
        # clock flattened).
        def tick(c, i):
            return bw_slot(f_slot(c, i), i), None

        carry, _ = lax.scan(tick, carry, jnp.arange(T, dtype=jnp.int32))
    saved, fbuf, bbuf, gacc, loss_acc, aux_acc, counts = carry

    # Only the last stage accumulated loss; psum-fwd/identity-bwd is
    # irrelevant here (no AD through this), plain psum replicates it.
    loss = lax.psum(loss_acc, pp_axis) / M
    if use_aux:
        # Mirror pp_loss: per-stage-chunk aux summed over the pipe,
        # averaged over stage-chunks × microbatches.
        loss = loss + moe_aux_weight * (
            lax.psum(aux_acc, pp_axis) / (n * v * M)
        )
    return loss, gacc, counts


def _1f1b_ticks(n: int, M: int, v: int) -> tuple[int, int]:
    """(last valid unit index, scan length T) of the 1F1B schedule —
    THE tick arithmetic, shared by the compiled schedule
    (``_pp_1f1b_loss_and_grads``) and the bubble accounting
    (``pp_bubble_fraction``) so the reported number cannot drift from
    the schedule that runs."""
    # Last VALID unit (m = M-1, c = v-1); off-group units past it are
    # bubbles anyway.
    j_last = ((M - 1) // n) * n * v + (v - 1) * n + (M - 1) % n
    # F span ends at j_last + (n-1); B span at (vn-1) + (n-1) + j_last.
    return j_last, j_last + v * n + n - 1


def _zb_segments(n: int, M: int, v: int) -> tuple[int, int, int, int]:
    """(warmup, steady, drain, f_end) tick-segment lengths of the zb
    schedule — THE zb tick arithmetic, shared by the compiled
    three-scan rendering and ``pp_bubble_fraction(schedule="zb")``.

    Warm-up ``[0, vn-1)`` runs F slots only (the first backward — unit
    0 on stage n-1 — cannot start before tick ``vn-1``); steady
    ``[vn-1, f_end)`` runs F+B+W; drain ``[f_end, T)`` runs B+W only
    (the last forward — unit j_last on stage n-1 — finishes at tick
    ``f_end - 1``).  The segments sum to the 1F1B scan length T, so zb
    changes per-tick slot CAPACITY, never the critical path.
    """
    j_last, T = _1f1b_ticks(n, M, v)
    warm = v * n - 1
    f_end = j_last + n
    return warm, f_end - warm, T - f_end, f_end


def pp_bubble_fraction(
    n: int, microbatches: int, virtual: int = 1, schedule: str = "1f1b"
) -> dict:
    """Exact slot accounting of a pipeline schedule's bubble.

    ``schedule="1f1b"``: the scan runs ``T`` iterations; each executes
    one F-unit and one B-unit slot of ``1/virtual`` stage-work each,
    masked off-schedule.  Useful work per device = ``2·M·virtual``
    unit-slots out of ``2·T`` — the rest is bubble (warm-up/drain
    idle).  ``T`` comes from ``_1f1b_ticks``, the same arithmetic the
    compiled schedule uses, so the number IS the schedule, not an
    estimate; the bench records it next to the wall-clock step times.

    ``schedule="zb"``: three phases (F, B, W) over the segmented scans
    of ``_zb_segments`` — slot capacity per stage is F-window + B-window
    + W-window = ``3·(j_last+n)`` for ``3·M·virtual`` useful slots, so
    the bubble is ``1 - M·v/(j_last+n)`` < the 1F1B fraction at every
    (n, M, v).  ``slot_windows`` (phase -> [start, end) tick) is the
    per-phase capacity table the measured-bubble reconstruction and
    the SL30x lint both consume.
    """
    M, v = microbatches, virtual
    j_last, T = _1f1b_ticks(n, M, v)
    if schedule == "zb":
        warm, steady, drain, f_end = _zb_segments(n, M, v)
        useful = 3 * M * v
        total = 3 * f_end
        return {
            "n_stages": n,
            "microbatches": M,
            "virtual": v,
            "schedule": "zb",
            "ticks": T,
            "segments": {"warmup": warm, "steady": steady, "drain": drain},
            "slot_windows": {
                "F": (0, f_end),
                "B": (v * n - 1, T),
                "W": (v * n - 1, T),
            },
            "useful_slots": useful,
            "slot_capacity": total,
            "bubble_fraction": round((total - useful) / total, 4),
            # per-device idle in full-stage-compute units: 3 slots/v
            # make up one stage-unit of F+B+W work.
            "bubble_stage_units": round((total - useful) / (3 * v), 4),
        }
    useful = 2 * M * v
    total = 2 * T
    return {
        "n_stages": n,
        "microbatches": M,
        "virtual": v,
        "schedule": "1f1b",
        "ticks": T,
        "slot_windows": {"F": (0, T), "B": (0, T)},
        "useful_slots": useful,
        "slot_capacity": total,
        "bubble_fraction": round((total - useful) / total, 4),
        # per-device idle in full-stage-compute units (ticks are 1/v of
        # a stage): the cross-virtual-degree comparable number.
        "bubble_stage_units": round((total - useful) / (2 * v), 4),
    }


def make_pp_train_step(
    cfg,
    *,
    mesh: Mesh,
    microbatches: int,
    data_axis: str = "data",
    pp_axis: str = "pipe",
    donate: bool = True,
    grad_sync: bool = True,
    moe_aux_weight: float = 0.01,
    zero: bool = False,
    schedule: str = "gpipe",
    grad_clip: float | None = None,
    virtual: int = 1,
):
    """Compiled DP x PP train step for a scanned TransformerLM config.

    ``virtual > 1`` selects INTERLEAVED scheduling (v layer chunks per
    stage; state must be placed with ``shard_state_pp(virtual=v)`` so
    each pipe position's contiguous rows are its round-robin chunks).
    Requires ``schedule="1f1b"`` or ``"zb"`` and
    ``num_layers % (n_stages · v) == 0``; see
    ``_pp_1f1b_loss_and_grads`` for the schedules and
    ``pp_bubble_fraction`` for the bubble accounting.

    ``schedule="zb"`` — zero-bubble ZB-H1-style W/B split (see
    ``_pp_1f1b_loss_and_grads``): bit-identical losses/grads to 1f1b,
    smaller bubble (``1 - Mv/(j_last+n)`` vs ``1 - Mv/T``), same
    activation memory.  v1 rejects ``cfg.cp_axis`` and the MoE aux
    loss.  The 1f1b and zb steps return measured per-stage
    ``pp_phase_counts`` (F/B/W useful-slot counters) in their metrics.

    ``zero=True``: ZeRO-1 over the data axis on the PIPE-LOCAL param
    shards — after the pipe psum completes every gradient, each
    position's local tree (its layer slice + the replicated leaves) is
    flattened, reduce-scattered over ``data_axis``, updated on the 1/N
    chunk, and gathered back.  Local sizes are uniform along the data
    axis and flat offsets identical across pipe positions, so the
    elementwise update keeps pipe-replicated leaves in lockstep — the
    same argument as ZeRO x TP.  Build the state with
    ``zero_state(..., pp_axis=...)``.

    ``step(state, batch, rng) -> (state, metrics)`` with
    ``batch = {"tokens": (B, S+1) int32}`` sharded over ``data_axis``
    (replicated over the pipe axis); the per-position rows must divide
    ``microbatches``.  State comes from ``shard_state_pp``.

    PP x TP: when ``cfg.tp_axis`` is set, each stage's blocks run
    Megatron-sharded over that (third) mesh axis; layer params shard over
    BOTH pipe (leading layer dim) and model (trailing dims).  Embeddings
    and head are computed replicated over the model axis (their grads
    complete through the blocks' copy/reduce operators), so only the
    pipe-axis psum below is needed for them.

    PP x CP: when ``cfg.cp_axis`` is set, the batch arrives pre-split as
    ``{"inputs", "targets"}`` sharded (rows → ``data_axis``, sequence →
    the cp axis; see ``shard_lm_batch`` — the next-token shift crosses
    seq shards so it must happen host-side), stage blocks run ring
    attention with global positions, and gradients are pmean'd over the
    cp axis after the pipe completion (the sequence-sharded loss's
    missing reduction, exactly as in ``make_train_step``).
    """
    from distributeddataparallel_tpu.models.transformer import (
        rope_frequencies,
    )
    from distributeddataparallel_tpu.ops.losses import lm_cross_entropy
    from distributeddataparallel_tpu.parallel.data_parallel import (
        all_reduce_gradients,
    )

    if not cfg.scan_layers:
        raise ValueError("pipeline parallelism requires scan_layers=True")
    if cfg.dropout_rate:
        raise ValueError("pipeline v1 does not support dropout")
    if zero and not grad_sync:
        # Same contract as make_train_step: the ZeRO reduce_scatter IS
        # the sync — it cannot be skipped.
        raise ValueError("grad_sync=False does not compose with zero=True")
    if grad_clip is not None and not grad_sync:
        # Same contract as make_train_step: unsynced per-replica grads
        # have per-replica norms — clipping would scale each data-axis
        # replica differently and params would drift.
        raise ValueError("grad_clip requires grad_sync=True")
    if schedule not in ("gpipe", "1f1b", "zb"):
        raise ValueError(f"unknown pipeline schedule {schedule!r}")
    if virtual < 1:
        raise ValueError(f"virtual must be >= 1, got {virtual}")
    if virtual > 1 and schedule == "gpipe":
        raise ValueError(
            "virtual (interleaved) stages require schedule='1f1b' — the "
            "GPipe path runs whole contiguous stages"
        )
    if schedule == "zb":
        if cfg.cp_axis is not None:
            raise ValueError(
                "zb schedule does not compose with cp_axis yet — use "
                "schedule='1f1b' for context-parallel pipelines"
            )
        if cfg.moe_experts > 0 and moe_aux_weight > 0.0:
            raise ValueError(
                "zb schedule does not support the MoE aux loss (the B/W "
                "split has no aux cotangent path) — set "
                "moe_aux_weight=0.0 or use schedule='1f1b'"
            )
    n_stages = mesh.shape[pp_axis]
    M = microbatches
    stack = _stage_stack(cfg, n_stages * virtual)

    def pp_loss(params, inputs, targets):
        """inputs/targets: (B_local, S_local) — the next-token shift
        already applied (host-side under CP, trivially otherwise)."""
        n = n_stages
        mb_rows = inputs.shape[0] // M
        S = inputs.shape[1]
        mbs_in = inputs.reshape(M, mb_rows, S)
        mbs_tgt = targets.reshape(M, mb_rows, S)
        positions = None
        n_cp = 1
        if cfg.cp_axis is not None:
            from distributeddataparallel_tpu.parallel.context_parallel import (
                cp_positions,
            )

            n_cp = int(lax.psum(1, cfg.cp_axis))
            positions = cp_positions(S, cfg.cp_axis)
        _check_seq_bound(cfg, S, n_cp)
        rope = (
            rope_frequencies(
                cfg.dims_per_head, cfg.max_seq_len, theta=cfg.rope_theta
            )
            if cfg.positional == "rope"
            else None
        )
        layer_shard = params["layers"]

        use_aux = cfg.moe_experts > 0 and moe_aux_weight > 0.0
        acc = jnp.zeros((), jnp.float32)
        aux_acc = jnp.zeros((), jnp.float32)

        def run_stage(x, t, s):
            nonlocal aux_acc
            if not use_aux:
                y, _ = stack.apply(
                    {"params": layer_shard}, x, positions, rope, True
                )
                return y
            (y, _), col = stack.apply(
                {"params": layer_shard}, x, positions, rope, True,
                mutable=["intermediates"],
            )
            from distributeddataparallel_tpu.models.transformer import (
                moe_aux_from_intermediates,
            )

            # Count only ticks where this stage processed a REAL
            # microbatch (stage s holds microbatch t - s).
            valid = jnp.logical_and(t - s >= 0, t - s < M)
            aux_acc = aux_acc + jnp.where(
                valid, moe_aux_from_intermediates(col), 0.0
            )
            return y

        def on_output(oi, y, s):
            nonlocal acc
            logits = _head(cfg, params, y)
            mb_loss = lm_cross_entropy(logits, mbs_tgt[oi])
            acc = acc + jnp.where(s == n - 1, mb_loss, 0.0)

        _pipeline_ticks(
            cfg, params, mbs_in, pp_axis=pp_axis, n=n, microbatches=M,
            run_stage=run_stage, on_output=on_output, positions=positions,
        )
        # Only the last stage accumulated; the psum replicates the total.
        # MUST be the custom-vjp reduce (psum fwd, identity bwd): under
        # check_vma=False, lax.psum's transpose psums the replicated
        # cotangent again, scaling every gradient by n_stages.  Under CP
        # this is still the LOCAL (per-seq-shard) loss; the seq reduction
        # happens outside the differentiated function.
        from distributeddataparallel_tpu.parallel.tensor_parallel import (
            reduce_from_tp,
        )

        loss = reduce_from_tp(acc, pp_axis) / M
        if use_aux:
            # Each stage accumulated its own layer slice's aux over its M
            # real ticks; the pipe psum completes the layer sum.  Mean
            # over stages x microbatches keeps the weight comparable to
            # the non-PP MoE loss.
            loss = loss + moe_aux_weight * (
                reduce_from_tp(aux_acc, pp_axis) / (n * M)
            )
        return loss

    def _step(state, batch, rng):
        if cfg.cp_axis is not None:
            inputs, targets = batch["inputs"], batch["targets"]
        else:
            toks = batch["tokens"]
            inputs, targets = toks[:, :-1], toks[:, 1:]
        if schedule in ("1f1b", "zb"):
            loss, grads, phase_counts = _pp_1f1b_loss_and_grads(
                cfg, stack, state.params, inputs, targets,
                pp_axis=pp_axis, n=n_stages, microbatches=M,
                moe_aux_weight=moe_aux_weight, virtual=virtual,
                schedule=schedule,
            )
        else:
            loss, grads = jax.value_and_grad(pp_loss)(
                state.params, inputs, targets
            )
            phase_counts = None
        # Complete replicated-param grads over the pipe (only the stages
        # that use them contributed); layer-slice grads stay local.
        gspecs = pp_param_specs(grads, pp_axis, cfg.tp_axis, cfg.ep_axis)
        grads = jax.tree.map(
            lambda g, sp: g if any(sp) else lax.psum(g, pp_axis),
            grads,
            gspecs,
        )
        if cfg.cp_axis is not None:
            # Complete the sequence-sharded gradient (model math, exactly
            # as in make_train_step's cp handling).
            grads = jax.tree.map(
                lambda g: lax.pmean(g, cfg.cp_axis), grads
            )
            loss = lax.pmean(loss, cfg.cp_axis)
        model_axes = tuple(
            ax for ax in (pp_axis, cfg.tp_axis, cfg.ep_axis)
            if ax is not None
        )
        if zero:
            from distributeddataparallel_tpu.parallel.zero import zero_update

            new_params, new_opt = zero_update(
                grads, state, data_axis, mesh.shape[data_axis],
                clip_norm=grad_clip, model_axes=model_axes,
                local_specs=gspecs if grad_clip is not None else None,
            )
            new_state = state.replace(
                step=state.step + 1, params=new_params, opt_state=new_opt
            )
        else:
            if grad_sync:
                grads = all_reduce_gradients(grads, data_axis, op="mean")
            if grad_clip is not None:
                # Axis-aware global norm: stage-local layer slices psum
                # over the pipe (and Megatron/expert) axes, replicated
                # leaves (complete per position after the psum above)
                # count once — identical on every position.
                from distributeddataparallel_tpu.parallel.data_parallel import (
                    clip_scale,
                    model_axes_sumsq,
                )

                scale = clip_scale(
                    jnp.sqrt(model_axes_sumsq(grads, gspecs)), grad_clip
                )
                grads = jax.tree.map(lambda g: g * scale, grads)
            new_state = state.apply_gradients(grads)
        metrics = {"loss": lax.pmean(loss, data_axis)}
        if phase_counts is not None:
            # Measured per-stage useful-slot counters, gathered over the
            # pipe into an (n_stages, 3) [F, B, W] table — identical on
            # every device, so the replicated out-spec is exact.  This
            # is the device-side half of the measured-bubble loop
            # (observability.pipeline reconstructs the fraction).
            metrics["pp_phase_counts"] = lax.all_gather(
                phase_counts, pp_axis
            )
        return new_state, metrics

    compiled = None
    jit_kwargs = {"donate_argnums": (0,)} if donate else {}

    if cfg.cp_axis is not None:
        batch_spec: Any = {
            "inputs": P(data_axis, cfg.cp_axis),
            "targets": P(data_axis, cfg.cp_axis),
        }
    else:
        batch_spec = P(data_axis)

    def step(state, batch, rng):
        nonlocal compiled
        if compiled is None:
            if zero:
                from distributeddataparallel_tpu.parallel.zero import (
                    state_specs,
                )

                specs = state_specs(
                    state, data_axis, cfg.tp_axis, cfg.ep_axis, pp_axis
                )
            else:
                specs = pp_state_specs(
                    state, pp_axis, cfg.tp_axis, cfg.ep_axis
                )
            sharded = jax.shard_map(
                _step,
                mesh=mesh,
                in_specs=(specs, batch_spec, P()),
                out_specs=(specs, P()),
                check_vma=False,
            )
            compiled = jax.jit(sharded, **jit_kwargs)
            step.jitted = compiled  # introspection: memory_analysis, AOT
        return compiled(state, batch, rng)

    step.jitted = None

    # Expected-collective manifest for the graph linter: activations
    # flow between stages via ppermute on the pipe axis; gradients
    # reduce over data (psum, or reduce_scatter/all_gather under ZeRO)
    # and over pipe for the replicated "rest" params.
    from distributeddataparallel_tpu.analysis.rules import (
        collective_manifest,
    )

    _any = {p: (0, None) for p in ("psum", "reduce_scatter",
                                   "psum_scatter", "all_gather",
                                   "ppermute", "all_to_all")}
    if zero:
        _data = {"reduce_scatter": (1, None), "all_gather": (1, None),
                 "psum": (0, None)}
    elif grad_sync:
        _data = {"psum": (1, None)}
    else:
        _data = {"psum": (0, None)}
    _reduce = {
        data_axis: _data,
        pp_axis: {"ppermute": (1, None), "psum": (0, None)},
    }
    for ax in (cfg.cp_axis, cfg.tp_axis, cfg.ep_axis):
        if ax is not None:
            _reduce.setdefault(ax, dict(_any))
    step.collective_manifest = collective_manifest(
        "pp-zero" if zero else "pp",
        grad_reduce=_reduce,
        donate=donate,
        allow_f32_reduce=True,
    )

    # Schedule-as-data for the SL3xx linter: the tick table this step
    # claims to run, rebuilt from the schedule definition (NOT from the
    # tick arithmetic above — the lint cross-checks the two, and
    # bubble_accounting is the factory-side number SL304 compares
    # against the table's).
    from distributeddataparallel_tpu.analysis.schedule_lint import (
        gpipe_schedule_ir,
        one_f_one_b_schedule_ir,
        zb_schedule_ir,
    )

    if schedule == "zb":
        step.schedule_ir = zb_schedule_ir(
            n_stages, M, virtual, hop_axis=pp_axis
        )
        step.bubble_accounting = pp_bubble_fraction(
            n_stages, M, virtual, schedule="zb"
        )
    elif schedule == "1f1b":
        step.schedule_ir = one_f_one_b_schedule_ir(
            n_stages, M, virtual, hop_axis=pp_axis
        )
        step.bubble_accounting = pp_bubble_fraction(n_stages, M, virtual)
    else:
        step.schedule_ir = gpipe_schedule_ir(n_stages, M, hop_axis=pp_axis)
        step.bubble_accounting = None
    return step
