"""Deterministic per-replica index sharding: the ``DistributedSampler`` analog.

The reference shards CIFAR-10 with
``DistributedSampler(dataset, num_replicas=world_size, rank=rank, shuffle=True)``
(ref dpp.py:34) and reshuffles per epoch via ``sampler.set_epoch(epoch)``
(ref dpp.py:46).  The semantics the build must reproduce (SURVEY.md §2b):

1. Optionally shuffle ``range(N)`` with a generator seeded ``seed + epoch``.
2. If not ``drop_last``: pad by repeating indices until
   ``total_size = ceil(N / num_replicas) * num_replicas`` so every replica
   gets the same count.  If ``drop_last``: truncate to the floor multiple.
3. Each replica takes the strided slice ``indices[rank::num_replicas]``.

On TPU this object feeds the *per-host* input pipeline: each host loads only
its replicas' rows and the global batch is assembled with
``jax.make_array_from_process_local_data`` (see ``data.loader``).  The
sampler itself is pure host-side NumPy — no device work.
"""

from __future__ import annotations

import math
from typing import Iterator, Sequence

import numpy as np


class DistributedSampler:
    """Epoch-seeded, padded, strided index shard for one replica.

    Matches torch's ``DistributedSampler`` contract (padding, striding,
    ``set_epoch``) without depending on torch.  ``dataset`` may be anything
    with ``__len__``, or an int length.
    """

    def __init__(
        self,
        dataset,
        num_replicas: int,
        rank: int,
        shuffle: bool = True,
        seed: int = 0,
        drop_last: bool = False,
    ):
        if not (0 <= rank < num_replicas):
            raise ValueError(f"rank {rank} not in [0, {num_replicas})")
        self.dataset_len = dataset if isinstance(dataset, int) else len(dataset)
        self.num_replicas = num_replicas
        self.rank = rank
        self.shuffle = shuffle
        self.seed = seed
        self.drop_last = drop_last
        self.epoch = 0
        if self.drop_last and self.dataset_len % num_replicas != 0:
            self.num_samples = self.dataset_len // num_replicas
        else:
            self.num_samples = math.ceil(self.dataset_len / num_replicas)
        self.total_size = self.num_samples * num_replicas

    def set_epoch(self, epoch: int) -> None:
        """Reseed the shuffle for a new epoch (analog of ref dpp.py:46)."""
        self.epoch = epoch

    def _global_indices(self) -> np.ndarray:
        if self.shuffle:
            rng = np.random.default_rng(self.seed + self.epoch)
            indices = rng.permutation(self.dataset_len)
        else:
            indices = np.arange(self.dataset_len)
        if self.drop_last:
            indices = indices[: self.total_size]
        else:
            pad = self.total_size - len(indices)
            if pad > 0:
                # Repeat from the head, wrapping if the dataset is smaller
                # than one full round — same rule torch uses.
                reps = math.ceil(pad / len(indices))
                indices = np.concatenate([indices, np.tile(indices, reps)[:pad]])
        return indices

    def local_indices(self) -> np.ndarray:
        """This replica's indices for the current epoch (rank::num_replicas)."""
        return self._global_indices()[self.rank :: self.num_replicas]

    def __iter__(self) -> Iterator[int]:
        return iter(self.local_indices().tolist())

    def __len__(self) -> int:
        return self.num_samples


def shard_indices_for_hosts(
    dataset_len: int,
    *,
    num_hosts: int,
    host_id: int,
    replicas_per_host: int,
    epoch: int = 0,
    seed: int = 0,
    shuffle: bool = True,
    drop_last: bool = False,
) -> np.ndarray:
    """Indices for all of one host's replicas, interleaved batch-compatibly.

    On TPU a host feeds ``replicas_per_host`` mesh positions at once.  This
    returns the concatenation of each local replica's strided shard in
    replica order, shaped ``(replicas_per_host, num_samples)`` — row r is
    global replica ``host_id * replicas_per_host + r``, exactly what that
    device would have received under 1-process-per-device DDP.
    """
    rows = []
    for r in range(replicas_per_host):
        s = DistributedSampler(
            dataset_len,
            num_replicas=num_hosts * replicas_per_host,
            rank=host_id * replicas_per_host + r,
            shuffle=shuffle,
            seed=seed,
            drop_last=drop_last,
        )
        s.set_epoch(epoch)
        rows.append(s.local_indices())
    return np.stack(rows)
