"""Tensor parallelism: Megatron-style sharded attention/MLP over a
``model`` mesh axis (beyond-reference scope; SURVEY.md §2c notes the mesh
design must not preclude a model axis — this module fills it in).

The TPU-native shape of TP (Shoeybi et al., arXiv 1909.08053 pattern,
re-expressed for shard_map + ICI collectives):

- Column-parallel projections (q/k/v, MLP up/gate) shard their OUTPUT
  features over the axis: each position holds ``H / tp`` attention heads
  and ``d_ff / tp`` hidden units.  Their biases shard with the features.
- Row-parallel projections (attention o, MLP down) shard their INPUT
  features; their partial outputs are summed over the axis with one
  ``psum`` per block — the only two collectives per layer, riding ICI.
- Activations entering a sharded region pass through ``copy_to_tp``
  (forward identity, backward psum) and leave through ``reduce_from_tp``
  (forward psum, backward identity) — the conjugate operator pair that
  makes every replicated parameter's gradient come out complete and
  identical on all positions, so the data-parallel gradient sync needs
  no TP-awareness at all.

Parameter layout is by NAME (``tp_param_specs``): the rules mirror the
module structure in ``models.transformer`` and tolerate scanned layers
(extra leading layer dim) by right-aligning the spec.
"""

from __future__ import annotations

import functools
from typing import Any

import jax
import jax.numpy as jnp
from jax import lax
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

Pytree = Any


def tp_size(axis_name: str | None) -> int:
    """Static size of the TP axis: the real size inside shard_map, 1 when
    the axis is unbound (direct apply / init — full, unsharded shapes)."""
    if axis_name is None:
        return 1
    try:
        return int(lax.psum(1, axis_name))
    except NameError:
        return 1


@functools.partial(jax.custom_vjp, nondiff_argnums=(1,))
def copy_to_tp(x, axis_name: str):
    """Identity forward, psum backward — entry into a sharded region.

    Downstream column-parallel layers consume the (replicated) input; in
    the backward pass each position produces only ITS shard's
    contribution to dx, and this operator's transpose completes it.
    """
    return x


def _copy_fwd(x, axis_name):
    return x, None


def _copy_bwd(axis_name, _, g):
    return (lax.psum(g, axis_name),)


copy_to_tp.defvjp(_copy_fwd, _copy_bwd)


@functools.partial(jax.custom_vjp, nondiff_argnums=(1,))
def reduce_from_tp(x, axis_name: str):
    """psum forward, identity backward — exit from a sharded region.

    Row-parallel layers produce partial sums; the forward psum completes
    them and the cotangent is already replicated, so the backward is the
    identity (a psum there would double-count).
    """
    return lax.psum(x, axis_name)


def _reduce_fwd(x, axis_name):
    return lax.psum(x, axis_name), None


def _reduce_bwd(axis_name, _, g):
    return (g,)


reduce_from_tp.defvjp(_reduce_fwd, _reduce_bwd)


# --- Parameter layout ----------------------------------------------------

#: path-suffix -> partition of the TRAILING dims (right-aligned against
#: the leaf, so scanned layers' leading layer dim stays unsharded).
_TP_RULES: tuple[tuple[tuple[str, str], tuple[str | None, ...]], ...] = (
    (("q_proj", "kernel"), (None, "model", None)),   # (d, H, D)
    (("k_proj", "kernel"), (None, "model", None)),
    (("v_proj", "kernel"), (None, "model", None)),
    (("q_proj", "bias"), ("model", None)),           # (H, D)
    (("k_proj", "bias"), ("model", None)),
    (("v_proj", "bias"), ("model", None)),
    (("o_proj", "kernel"), ("model", None, None)),   # (H, D, d)
    (("o_proj", "bias"), (None,)),                   # added after the psum
    (("up_proj", "kernel"), (None, "model")),        # (d, f)
    (("gate_proj", "kernel"), (None, "model")),
    (("up_proj", "bias"), ("model",)),
    (("gate_proj", "bias"), ("model",)),
    (("down_proj", "kernel"), ("model", None)),      # (f, d)
    (("down_proj", "bias"), (None,)),                # added after the psum
)


def _spec_for_path(path: tuple[str, ...], leaf, axis_name: str) -> P:
    for suffix, dims in _TP_RULES:
        if path[-len(suffix):] == suffix:
            trailing = tuple(
                axis_name if d == "model" else None for d in dims
            )
            pad = leaf.ndim - len(trailing)
            if pad < 0:
                raise ValueError(
                    f"param {'/'.join(path)} has rank {leaf.ndim}, "
                    f"expected >= {len(trailing)}"
                )
            if not any(trailing):
                return P()  # canonical fully-replicated form
            return P(*((None,) * pad + trailing))
    return P()


def tp_param_specs(params: Pytree, axis_name: str = "model") -> Pytree:
    """PartitionSpec tree for a TransformerLM param tree under TP."""
    flat = jax.tree_util.tree_flatten_with_path(params)[0]
    treedef = jax.tree.structure(params)
    specs = []
    for path, leaf in flat:
        names = tuple(
            getattr(k, "key", getattr(k, "name", str(k))) for k in path
        )
        specs.append(_spec_for_path(names, leaf, axis_name))
    return jax.tree.unflatten(treedef, specs)


def tp_state_specs(state, axis_name: str = "model") -> Pytree:
    """Spec tree for a whole TrainState.

    Optimizer state gets the SAME path-suffix rules as params: optax
    state trees embed the param tree (e.g. ``.../trace/.../q_proj/kernel``
    for momentum, mu/nu for adam), so the suffix match lands on the right
    leaves, and scalars like step counts match no rule → replicated.
    """
    return state.replace(
        step=P(),
        params=tp_param_specs(state.params, axis_name),
        opt_state=tp_param_specs(state.opt_state, axis_name),
        model_state=jax.tree.map(lambda _: P(), state.model_state),
    )


def shard_state_tp(state, mesh: Mesh, axis_name: str = "model"):
    """Place a (host/full) TrainState on the mesh with TP param sharding —
    the TP analog of ``broadcast_params`` (which fully replicates)."""
    specs = tp_state_specs(state, axis_name)
    n = mesh.shape[axis_name]
    for (path, leaf), spec in zip(
        jax.tree_util.tree_flatten_with_path(state.params)[0],
        jax.tree.leaves(specs.params),
    ):
        for dim, name in enumerate(spec):
            if name == axis_name and leaf.shape[dim] % n:
                pretty = "/".join(
                    str(getattr(k, "key", k)) for k in path
                )
                raise ValueError(
                    f"TP degree {n} does not divide dim {dim} of param "
                    f"{pretty} (shape {leaf.shape}) — the model's head/"
                    f"kv-head/d_ff counts must all be divisible by the "
                    f"size of the {axis_name!r} mesh axis"
                )
    return jax.tree.map(
        lambda x, s: jax.device_put(x, NamedSharding(mesh, s)),
        state,
        specs,
    )
