"""Fully-sharded data parallelism (FSDP / ZeRO-3) for scanned LMs.

The reference is plain DDP — every rank holds full params, grads, and
optimizer state (ref dpp.py:39,41).  ``parallel.zero`` shards the
optimizer state (ZeRO-1); this module shards EVERYTHING: params, grads,
and optimizer state are all 1/N-resident per device, with full weights
existing only one layer at a time.  It is the torch-FSDP capability
re-derived for the TPU stack, where the whole wrapper collapses into
three facts:

1. **Storage** is the scanned layer stack flattened per layer — a
   single (L, chunk) f32 array whose chunk dim is sharded over the
   ``data`` axis — plus one flat vector for the non-layer params
   (embeddings, final norm, head), sharded the same way.
2. **Compute** is a ``lax.scan`` over layers whose body ``all_gather``s
   just the current layer's chunk, unflattens it, and applies the SAME
   ``DecoderBlock`` the model uses.  Under ``cfg.remat`` the body is
   ``jax.checkpoint``ed, so the backward re-gathers each layer instead
   of keeping it live — peak weight memory is one layer, forward and
   backward.
3. **Gradient sync needs no code at all**: the AD transpose of
   ``all_gather`` IS ``psum_scatter``, so differentiating the forward
   produces reduce-scattered (1/N) gradients in exactly the storage
   layout — torch-FSDP's backward hooks, flat-param wrappers, and
   reduce-scatter machinery fall out of one autodiff rule.

The elementwise optax update then runs directly on the sharded flats
(same restriction as ZeRO-1: transforms needing global tensor structure
don't apply).  ``fsdp_gather_params`` reassembles the full tree for
checkpoints / generation / weight interchange.

v1 scope: scanned TransformerLM configs (``scan_layers=True``, no
dropout), pure DP mesh — no TP/PP/CP/EP composition (rejected loudly).
"""

from __future__ import annotations

from typing import Any

import jax
import jax.numpy as jnp
import optax
from jax import lax
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from distributeddataparallel_tpu.parallel.zero import flat_size, unflatten

Pytree = Any


def _abstract_params(cfg):
    from distributeddataparallel_tpu.models.transformer import TransformerLM

    if not cfg.scan_layers:
        raise ValueError("FSDP requires scan_layers=True")
    if cfg.dropout_rate:
        raise ValueError("FSDP v1 does not support dropout")
    for axis in (cfg.cp_axis, cfg.tp_axis, cfg.ep_axis):
        if axis is not None:
            raise ValueError(
                "FSDP v1 is pure data parallelism: unset cp/tp/ep_axis"
            )
    return jax.eval_shape(
        lambda: TransformerLM(cfg).init(
            jax.random.PRNGKey(0), jnp.zeros((1, 2), jnp.int32)
        )["params"]
    )


class _Meta:
    """Static flat-layout bookkeeping shared by state build and step."""

    def __init__(self, cfg, n: int):
        aparams = _abstract_params(cfg)
        self.cfg = cfg
        self.n = n
        self.L = cfg.num_layers
        layers = aparams["layers"]
        # Single-layer template: the stacked leading dim stripped.
        self.layer_template = jax.tree.map(
            lambda s: jax.ShapeDtypeStruct(s.shape[1:], s.dtype), layers
        )
        self.rest_template = {
            k: v for k, v in aparams.items() if k != "layers"
        }
        _, self.layer_chunk = flat_size(self.layer_template, n)
        _, self.rest_chunk = flat_size(self.rest_template, n)

    def flatten_full(self, params: Pytree) -> dict:
        """Full param tree -> {"layers": (L, layer_chunk*n) f32,
        "rest": (rest_chunk*n,) f32}, assembled HOST-SIDE with numpy —
        at the 8B scale this feature exists for, a full f32 flat on one
        device would not fit its HBM (the subsequent device_put moves
        each position only its shard)."""
        import numpy as np

        # jax.tree.leaves everywhere: canonical (sorted-key) order, the
        # same order zero.unflatten walks the template in.
        lay = np.concatenate(
            [
                np.asarray(l, np.float32).reshape(self.L, -1)
                for l in jax.tree.leaves(params["layers"])
            ],
            axis=1,
        )
        lay = np.pad(
            lay, ((0, 0), (0, self.layer_chunk * self.n - lay.shape[1]))
        )
        rest_leaves = [
            np.asarray(l, np.float32).reshape(-1)
            for l in jax.tree.leaves(
                {k: v for k, v in params.items() if k != "layers"}
            )
        ]
        rest = (
            np.concatenate(rest_leaves)
            if rest_leaves else np.zeros((0,), np.float32)
        )
        rest = np.pad(rest, (0, self.rest_chunk * self.n - rest.shape[0]))
        return {"layers": lay, "rest": rest}

    def unflatten_full(self, flat: dict) -> Pytree:
        """Inverse of flatten_full (full, gathered flats)."""
        rest = unflatten(flat["rest"], self.rest_template)
        layer_rows = [
            unflatten(flat["layers"][i], self.layer_template)
            for i in range(self.L)
        ]
        layers = jax.tree.map(
            lambda *rows: jnp.stack(rows), *layer_rows
        )
        return {"layers": layers, **rest}

    def param_specs(self, axis_name: str) -> dict:
        return {"layers": P(None, axis_name), "rest": P(axis_name)}

    def flat_leaf_spec(self, leaf, axis_name: str) -> P:
        """Spec for opt-state leaves mirroring the flat params: the
        (L, chunk) stacks shard their chunk dim, flat vectors shard
        whole, scalars replicate."""
        if getattr(leaf, "ndim", 0) == 2:
            return P(None, axis_name)
        if getattr(leaf, "ndim", 0) == 1:
            return P(axis_name)
        return P()


def fsdp_state(
    cfg,
    params: Pytree,
    tx: optax.GradientTransformation,
    mesh: Mesh,
    *,
    apply_fn=None,
    axis_name: str = "data",
):
    """Build the fully-sharded TrainState from a full param tree.

    params/grads/opt state are all 1/N per device; cross-device bytes
    exist only transiently inside the step's per-layer gathers.
    """
    from distributeddataparallel_tpu.training.state import TrainState

    n = mesh.shape[axis_name]
    meta = _Meta(cfg, n)
    flat = meta.flatten_full(params)
    flat = jax.tree.map(
        lambda x, s: jax.device_put(x, NamedSharding(mesh, s)),
        flat,
        meta.param_specs(axis_name),
    )

    def init_opt(local_flat):
        return tx.init(local_flat)

    opt_shapes = jax.eval_shape(
        tx.init,
        {
            "layers": jax.ShapeDtypeStruct(
                (meta.L, meta.layer_chunk), jnp.float32
            ),
            "rest": jax.ShapeDtypeStruct((meta.rest_chunk,), jnp.float32),
        },
    )
    opt_specs = jax.tree.map(
        lambda s: meta.flat_leaf_spec(s, axis_name), opt_shapes
    )
    opt_state = jax.jit(
        jax.shard_map(
            init_opt,
            mesh=mesh,
            in_specs=(meta.param_specs(axis_name),),
            out_specs=opt_specs,
            check_vma=False,
        )
    )(flat)
    return TrainState(
        step=jax.device_put(
            jnp.zeros((), jnp.int32), NamedSharding(mesh, P())
        ),
        params=flat,
        opt_state=opt_state,
        model_state={},
        apply_fn=apply_fn,
        tx=tx,
    )


def fsdp_gather_params(cfg, state, mesh: Mesh, axis_name: str = "data"):
    """Reassemble the full (replicated) param tree from the sharded flats
    — for checkpoint interchange, evaluation, or generation."""
    meta = _Meta(cfg, mesh.shape[axis_name])
    full_flat = jax.tree.map(
        lambda x: jax.device_put(x, NamedSharding(mesh, P())), state.params
    )
    return meta.unflatten_full(full_flat)


def make_fsdp_train_step(
    cfg,
    *,
    mesh: Mesh,
    data_axis: str = "data",
    donate: bool = True,
    grad_clip: float | None = None,
    accum_steps: int = 1,
):
    """Compiled FSDP train step for a scanned TransformerLM config.

    ``step(state, batch, rng) -> (state, metrics)`` with
    ``batch = {"tokens": (B_local, S+1) int32}`` sharded over
    ``data_axis`` and ``state`` from ``fsdp_state``.  Per layer, the
    forward gathers 1/N-sharded weights, computes, and discards; the
    backward re-gathers (``cfg.remat``) and reduce-scatters gradients —
    both directions emerge from AD of the all_gather, no hooks anywhere.

    ``accum_steps`` accumulates microbatch gradients IN THE SHARDED
    layout (each microbatch's reduce-scatter lands on the 1/N flats and
    sums there) — like torch FSDP under no_sync, every microbatch still
    re-gathers the weights; only the optimizer step is amortized.
    """
    if accum_steps < 1:
        raise ValueError(f"accum_steps must be >= 1, got {accum_steps}")
    from distributeddataparallel_tpu.models.transformer import (
        DecoderBlock,
        rope_frequencies,
    )
    from distributeddataparallel_tpu.ops.losses import lm_cross_entropy
    from distributeddataparallel_tpu.parallel.pipeline_parallel import (
        _check_seq_bound,
        _embed,
        _head,
    )

    n = mesh.shape[data_axis]
    meta = _Meta(cfg, n)
    block = DecoderBlock(cfg)

    def _replica_step(state, batch, rng):
        toks = batch["tokens"]
        inputs, targets = toks[:, :-1], toks[:, 1:]
        S = inputs.shape[1]
        _check_seq_bound(cfg, S)
        rope = (
            rope_frequencies(
                cfg.dims_per_head, cfg.max_seq_len, theta=cfg.rope_theta
            )
            if cfg.positional == "rope"
            else None
        )

        def loss_fn(flat, inputs, targets):
            rest_vec = lax.all_gather(
                flat["rest"], data_axis, axis=0, tiled=True
            )
            rest = unflatten(rest_vec, meta.rest_template)
            x = _embed(cfg, rest, inputs)

            def body(x, layer_row):
                vec = lax.all_gather(
                    layer_row, data_axis, axis=0, tiled=True
                )
                lp = unflatten(vec, meta.layer_template)
                y = block.apply({"params": lp["block"]}, x, None, rope, True)
                return y, None

            if cfg.remat:
                body = jax.checkpoint(body, prevent_cse=False)
            x, _ = lax.scan(body, x, flat["layers"])
            logits = _head(cfg, rest, x)
            return lm_cross_entropy(logits, targets)

        if accum_steps == 1:
            loss, gflat = jax.value_and_grad(loss_fn)(
                state.params, inputs, targets
            )
        else:
            if inputs.shape[0] % accum_steps:
                raise ValueError(
                    f"per-replica batch {inputs.shape[0]} not divisible "
                    f"by accum_steps={accum_steps}"
                )
            mb = inputs.shape[0] // accum_steps
            mbs_in = inputs.reshape(accum_steps, mb, S)
            mbs_tgt = targets.reshape(accum_steps, mb, S)

            def acc_body(carry, xs):
                acc_g, acc_l = carry
                i, t = xs
                l, g = jax.value_and_grad(loss_fn)(state.params, i, t)
                return (
                    jax.tree.map(jnp.add, acc_g, g), acc_l + l
                ), None

            # Grad shapes ARE the flat param shapes (no eval_shape trace).
            zeros = jax.tree.map(
                lambda p: jnp.zeros(p.shape, p.dtype), state.params
            )
            (gflat, loss), _ = lax.scan(
                acc_body, (zeros, jnp.zeros((), jnp.float32)),
                (mbs_in, mbs_tgt),
            )
            inv = 1.0 / accum_steps
            gflat = jax.tree.map(lambda g: g * inv, gflat)
            loss = loss * inv
        # The all_gather transpose SUMMED per-replica contributions into
        # each shard; divide for DDP mean semantics (global loss is the
        # mean of per-replica means).
        gflat = jax.tree.map(lambda g: g / n, gflat)
        if grad_clip is not None:
            # The flat shards partition the gradient vector: global
            # norm² is one psum of local sum-of-squares — exact.
            from distributeddataparallel_tpu.parallel.data_parallel import (
                clip_scale,
                sumsq_f32,
            )

            gnorm = jnp.sqrt(lax.psum(sumsq_f32(gflat), data_axis))
            gflat = jax.tree.map(
                lambda g: g * clip_scale(gnorm, grad_clip), gflat
            )
        new_state = state.apply_gradients(gflat)
        return new_state, {"loss": lax.pmean(loss, data_axis)}

    compiled = None
    jit_kwargs = {"donate_argnums": (0,)} if donate else {}

    def step(state, batch, rng):
        nonlocal compiled
        if compiled is None:
            opt_specs = jax.tree.map(
                lambda l: meta.flat_leaf_spec(l, data_axis),
                state.opt_state,
            )
            specs = state.replace(
                step=P(),
                params=meta.param_specs(data_axis),
                opt_state=opt_specs,
                model_state={},
            )
            sharded = jax.shard_map(
                _replica_step,
                mesh=mesh,
                in_specs=(specs, P(data_axis), P()),
                out_specs=(specs, P()),
                check_vma=False,
            )
            compiled = jax.jit(sharded, **jit_kwargs)
        return compiled(state, batch, rng)

    return step
