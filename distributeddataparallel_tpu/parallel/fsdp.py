"""Fully-sharded data parallelism (FSDP / ZeRO-3) for scanned LMs.

The reference is plain DDP — every rank holds full params, grads, and
optimizer state (ref dpp.py:39,41).  ``parallel.zero`` shards the
optimizer state (ZeRO-1); this module shards EVERYTHING: params, grads,
and optimizer state are all 1/N-resident per device, with full weights
existing only one layer at a time.  It is the torch-FSDP capability
re-derived for the TPU stack, where the whole wrapper collapses into
three facts:

1. **Storage** is the scanned layer stack flattened per layer — a
   single (L, chunk) f32 array whose chunk dim is sharded over the
   ``data`` axis — plus one flat vector for the non-layer params
   (embeddings, final norm, head), sharded the same way.
2. **Compute** is a ``lax.scan`` over layers whose body ``all_gather``s
   just the current layer's chunk, unflattens it, and applies the SAME
   ``DecoderBlock`` the model uses.  Under ``cfg.remat`` the body is
   ``jax.checkpoint``ed, so the backward re-gathers each layer instead
   of keeping it live — peak weight memory is one layer, forward and
   backward.  The non-layer flat is gathered separately around its two
   uses (embedding in, head out) and checkpointed the same way, so the
   full embeddings/head are never co-resident with the layer scan.
3. **Gradient sync needs no code at all**: the AD transpose of
   ``all_gather`` IS ``psum_scatter``, so differentiating the forward
   produces reduce-scattered (1/N) gradients in exactly the storage
   layout — torch-FSDP's backward hooks, flat-param wrappers, and
   reduce-scatter machinery fall out of one autodiff rule.

The elementwise optax update then runs directly on the sharded flats
(same restriction as ZeRO-1: transforms needing global tensor structure
don't apply).

v2 additions over the round-2 v1:

- **TP composition** (``tp_axis``): flats store each model position's
  Megatron shard (model-major layout); the step still gathers over the
  DATA axis only — each model position reconstitutes its own TP-local
  layer and the block's conjugate operators do the rest.  The non-layer
  flat is replicated per model position (standard Megatron embedding
  placement).
- **bf16 gathers** (``gather_dtype``): the f32 master flats are cast to
  the gather dtype BEFORE the all_gather — half the collective bytes
  and half the gathered-weight residency.  Norm scales ride along in
  the lower precision (the torch-FSDP ``MixedPrecision(param_dtype=)``
  trade).
- **Streaming eval** (``make_fsdp_eval_step``): masked forward-only
  metrics with the same per-layer gathers as training — the full
  replicated tree is never materialized on device.
- **Host gather** (``fsdp_gather_params(..., host=True)``): assembles
  the full tree in host RAM shard by shard for checkpoint interchange
  and generation at scales where a device-side gather would OOM.

v2 scope: scanned TransformerLM configs (``scan_layers=True``, no
dropout), DP x TP meshes — no CP/EP composition (rejected loudly).
grad_clip composes with TP via a duplicate-de-weighted flat norm (each
position's flat holds a full copy of the replicated leaves and the rest
flat; those elements count 1/n_tp before the (data, tp) psum).
"""

from __future__ import annotations

from typing import Any

import jax
import jax.numpy as jnp
import numpy as np
import optax
from jax import lax
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from distributeddataparallel_tpu.parallel.zero import flat_size, unflatten

Pytree = Any


def _abstract_params(cfg):
    from distributeddataparallel_tpu.models.transformer import TransformerLM

    if not cfg.scan_layers:
        raise ValueError("FSDP requires scan_layers=True")
    if cfg.dropout_rate:
        raise ValueError("FSDP does not support dropout")
    for axis in (cfg.cp_axis, cfg.ep_axis):
        if axis is not None:
            raise ValueError("FSDP v2 composes with TP only: unset cp/ep_axis")
    # eval_shape outside shard_map: tp_size() sees no bound axis, so the
    # shapes come out FULL (unsharded) regardless of cfg.tp_axis.
    return jax.eval_shape(
        lambda: TransformerLM(cfg).init(
            jax.random.PRNGKey(0), jnp.zeros((1, 2), jnp.int32)
        )["params"]
    )


def _path_names(path) -> tuple:
    return tuple(str(getattr(k, "key", getattr(k, "name", k))) for k in path)


class _Meta:
    """Static flat-layout bookkeeping shared by state build and steps.

    With ``n_tp > 1`` the layer templates are TP-LOCAL (Megatron-sharded
    dims divided by ``n_tp``) and the global flats lay the model
    positions out major: ``layers`` is ``(L, n_tp * layer_chunk * n)``
    sharded ``P(None, (tp_axis, data_axis))`` so position ``(j, k)``
    holds data-chunk ``k`` of model shard ``j``; ``rest`` tiles one
    replicated copy per model position the same way.
    """

    def __init__(self, cfg, n: int, tp_axis: str | None = None, n_tp: int = 1):
        from distributeddataparallel_tpu.parallel.tensor_parallel import (
            _spec_for_path,
        )

        if (tp_axis is None) != (cfg.tp_axis is None):
            # A cfg.tp_axis with full (non-localized) templates would run
            # the Megatron psums over full weights — silently wrong, not
            # a shape error.
            raise ValueError(
                "pass tp_axis to BOTH the config and the FSDP entry point"
            )
        aparams = _abstract_params(cfg)
        self.cfg = cfg
        self.n = n
        self.tp_axis = tp_axis
        self.n_tp = n_tp if tp_axis is not None else 1
        self.L = cfg.num_layers
        self._tp_rule = _spec_for_path
        layers = aparams["layers"]
        # Single-layer template: the stacked leading dim stripped, then
        # Megatron-sharded dims divided for the TP-local view.
        full_layer = jax.tree.map(
            lambda s: jax.ShapeDtypeStruct(s.shape[1:], s.dtype), layers
        )
        self.layer_template = self._localize(full_layer)
        self.rest_template = {
            k: v for k, v in aparams.items() if k != "layers"
        }
        _, self.layer_chunk = flat_size(self.layer_template, n)
        _, self.rest_chunk = flat_size(self.rest_template, n)

    def _localize(self, template: Pytree) -> Pytree:
        if self.n_tp == 1:
            return template
        flat = jax.tree_util.tree_flatten_with_path(template)[0]
        treedef = jax.tree.structure(template)
        leaves = []
        for path, leaf in flat:
            spec = self._tp_rule(_path_names(path), leaf, "model")
            shape = list(leaf.shape)
            for dim, name in enumerate(spec):
                if name == "model":
                    if shape[dim] % self.n_tp:
                        raise ValueError(
                            f"tp={self.n_tp} does not divide dim {dim} of "
                            f"{'/'.join(_path_names(path))} {tuple(shape)}"
                        )
                    shape[dim] //= self.n_tp
            leaves.append(jax.ShapeDtypeStruct(tuple(shape), leaf.dtype))
        return jax.tree.unflatten(treedef, leaves)

    def _model_dim(self, names, ndim: int) -> int | None:
        """Which dim of a STACKED (leading L) layer leaf is Megatron-
        sharded, or None."""
        probe = jax.ShapeDtypeStruct((1,) * ndim, jnp.float32)
        spec = self._tp_rule(names, probe, "model")
        for dim, name in enumerate(spec):
            if name == "model":
                return dim
        return None

    def flatten_full(self, params: Pytree) -> dict:
        """Full param tree -> the sharded-flat layout, assembled
        HOST-SIDE with numpy — at the 8B scale this feature exists for,
        a full f32 flat on one device would not fit its HBM (the
        subsequent device_put moves each position only its shard)."""
        parts = []
        for j in range(self.n_tp):
            rows = []
            # jax.tree.leaves order everywhere: canonical (sorted-key)
            # order, the same order zero.unflatten walks the template in.
            for path, leaf in jax.tree_util.tree_flatten_with_path(
                params["layers"]
            )[0]:
                arr = np.asarray(leaf, np.float32)
                dim = (
                    self._model_dim(_path_names(path), arr.ndim)
                    if self.n_tp > 1 else None
                )
                if dim is not None:
                    size = arr.shape[dim] // self.n_tp
                    arr = np.take(
                        arr, range(j * size, (j + 1) * size), axis=dim
                    )
                rows.append(arr.reshape(self.L, -1))
            loc = np.concatenate(rows, axis=1)
            parts.append(np.pad(
                loc, ((0, 0), (0, self.layer_chunk * self.n - loc.shape[1]))
            ))
        lay = np.concatenate(parts, axis=1)
        rest_leaves = [
            np.asarray(l, np.float32).reshape(-1)
            for l in jax.tree.leaves(self.rest_of(params))
        ]
        rest = (
            np.concatenate(rest_leaves)
            if rest_leaves else np.zeros((0,), np.float32)
        )
        rest = np.pad(rest, (0, self.rest_chunk * self.n - rest.shape[0]))
        return {"layers": lay, "rest": np.tile(rest, self.n_tp)}

    @staticmethod
    def rest_of(params: Pytree) -> dict:
        return {k: v for k, v in params.items() if k != "layers"}

    def unflatten_full(self, flat: dict) -> Pytree:
        """Inverse of flatten_full (full, gathered flats): TP-local
        segments unflattened per model position, sharded dims
        re-concatenated.  Numpy inputs assemble entirely in numpy —
        jnp.stack/concatenate would commit the ~full-tree intermediates
        to a device, defeating the host=True gather."""
        xp = np if isinstance(flat["layers"], np.ndarray) else jnp
        rest = unflatten(
            flat["rest"][: self.rest_chunk * self.n], self.rest_template
        )
        seg_w = self.layer_chunk * self.n
        per_j = []
        for j in range(self.n_tp):
            seg = flat["layers"][:, j * seg_w:(j + 1) * seg_w]
            rows = [
                unflatten(seg[i], self.layer_template)
                for i in range(self.L)
            ]
            per_j.append(jax.tree.map(lambda *r: xp.stack(r), *rows))
        if self.n_tp == 1:
            return {"layers": per_j[0], **rest}
        flat0, treedef = jax.tree_util.tree_flatten_with_path(per_j[0])
        leaves = []
        for i, (path, leaf0) in enumerate(flat0):
            dim = self._model_dim(_path_names(path), leaf0.ndim)
            if dim is None:
                leaves.append(leaf0)  # replicated: any position's copy
            else:
                leaves.append(xp.concatenate(
                    [jax.tree.leaves(t)[i] for t in per_j], axis=dim
                ))
        return {
            "layers": jax.tree_util.tree_unflatten(treedef, leaves), **rest
        }

    def shard_axes(self, data_axis: str):
        return (
            (self.tp_axis, data_axis) if self.n_tp > 1 else data_axis
        )

    def param_specs(self, axis_name: str) -> dict:
        ax = self.shard_axes(axis_name)
        return {"layers": P(None, ax), "rest": P(ax)}

    def flat_leaf_spec(self, leaf, axis_name: str) -> P:
        """Spec for opt-state leaves mirroring the flat params: the
        (L, chunk) stacks shard their chunk dim, flat vectors shard
        whole, scalars replicate."""
        ax = self.shard_axes(axis_name)
        if getattr(leaf, "ndim", 0) == 2:
            return P(None, ax)
        if getattr(leaf, "ndim", 0) == 1:
            return P(ax)
        return P()

    def gather_template(self, template: Pytree, dtype) -> Pytree:
        """The template at the gather dtype (bf16 gathers unflatten to
        bf16 leaves; None keeps the f32 master dtype)."""
        if dtype is None:
            return template
        return jax.tree.map(
            lambda s: jax.ShapeDtypeStruct(s.shape, dtype), template
        )


def fsdp_state(
    cfg,
    params: Pytree,
    tx: optax.GradientTransformation,
    mesh: Mesh,
    *,
    apply_fn=None,
    axis_name: str = "data",
    tp_axis: str | None = None,
):
    """Build the fully-sharded TrainState from a full param tree.

    params/grads/opt state are all 1/N per device; cross-device bytes
    exist only transiently inside the step's per-layer gathers.  With
    ``tp_axis`` the flats additionally split Megatron shards over the
    model axis (1/(N*TP) layer residency per device).
    """
    from distributeddataparallel_tpu.training.state import TrainState

    n = mesh.shape[axis_name]
    n_tp = mesh.shape[tp_axis] if tp_axis is not None else 1
    meta = _Meta(cfg, n, tp_axis, n_tp)
    flat = meta.flatten_full(params)
    flat = jax.tree.map(
        lambda x, s: jax.device_put(x, NamedSharding(mesh, s)),
        flat,
        meta.param_specs(axis_name),
    )

    opt_shapes = jax.eval_shape(
        tx.init,
        {
            "layers": jax.ShapeDtypeStruct(
                (meta.L, meta.layer_chunk), jnp.float32
            ),
            "rest": jax.ShapeDtypeStruct((meta.rest_chunk,), jnp.float32),
        },
    )
    opt_specs = jax.tree.map(
        lambda s: meta.flat_leaf_spec(s, axis_name), opt_shapes
    )
    opt_state = jax.jit(
        jax.shard_map(
            tx.init,
            mesh=mesh,
            in_specs=(meta.param_specs(axis_name),),
            out_specs=opt_specs,
            check_vma=False,
        )
    )(flat)
    return TrainState(
        step=jax.device_put(
            jnp.zeros((), jnp.int32), NamedSharding(mesh, P())
        ),
        params=flat,
        opt_state=opt_state,
        model_state={},
        apply_fn=apply_fn,
        tx=tx,
    )


def fsdp_gather_params(
    cfg,
    state,
    mesh: Mesh,
    axis_name: str = "data",
    tp_axis: str | None = None,
    *,
    host: bool = False,
):
    """Reassemble the full param tree from the sharded flats — for
    checkpoint interchange, evaluation, or generation.

    ``host=False`` materializes the tree REPLICATED on every device:
    fine at small scale, guaranteed OOM at the 8B scale FSDP exists for
    (a full f32 tree is ~30 GB).  ``host=True`` pulls the flats into
    host RAM and assembles with numpy — no device memory spike; the
    caller decides what (if anything) goes back to device, e.g. a bf16
    cast for decoding.  Prefer ``make_fsdp_eval_step`` for evaluation —
    it never forms the full tree at all.
    """
    n_tp = mesh.shape[tp_axis] if tp_axis is not None else 1
    meta = _Meta(cfg, mesh.shape[axis_name], tp_axis, n_tp)
    if host:
        if jax.process_count() > 1:
            # Multi-host host-RAM gather: host assembly fed by BOUNDED
            # device resharding.  device_get cannot fetch non-addressable
            # shards, and replicating the whole flats on device would
            # reintroduce the HBM spike this path exists to avoid — so
            # the exchange is chunked: one layer row (resp. one data
            # chunk of the rest flat) per collective, replicated to every
            # process and pulled straight to numpy.  Peak HBM = one
            # layer's full flat — the same granularity the training
            # step's per-layer all_gather already commits to.
            # COLLECTIVE: every process must call this together (it
            # compiles and runs resharding programs), exactly like a
            # training step.
            rep = NamedSharding(mesh, P())
            take_row = jax.jit(
                lambda a, i: lax.dynamic_index_in_dim(
                    a, i, 0, keepdims=False
                ),
                out_shardings=rep,
            )
            lay = np.stack([
                np.asarray(
                    take_row(state.params["layers"], i).addressable_data(0)
                )
                for i in range(meta.L)
            ])
            take_chunk = jax.jit(
                lambda a, k: lax.dynamic_slice(
                    a, (k * meta.rest_chunk,), (meta.rest_chunk,)
                ),
                out_shardings=rep,
            )
            rest = np.concatenate([
                np.asarray(
                    take_chunk(state.params["rest"], k).addressable_data(0)
                )
                for k in range(meta.n)
            ])
            # unflatten_full reads only tp-position 0's rest block, which
            # is exactly what `rest` holds.
            return jax.tree.map(
                np.asarray,
                meta.unflatten_full({"layers": lay, "rest": rest}),
            )
        full_flat = jax.tree.map(
            lambda x: np.asarray(jax.device_get(x)), state.params
        )
        return jax.tree.map(
            np.asarray, meta.unflatten_full(full_flat)
        )
    full_flat = jax.tree.map(
        lambda x: jax.device_put(x, NamedSharding(mesh, P())), state.params
    )
    return meta.unflatten_full(full_flat)


def _forward_pieces(cfg, meta, *, data_axis: str, gather_dtype):
    """The shared embed -> layer-scan -> head forward over sharded flats
    (training loss and streaming eval both build on this).

    Returns ``forward(flat, inputs) -> logits`` plus the rope tables.
    Each piece gathers what it needs and is checkpointed under
    ``cfg.remat`` so the backward re-gathers instead of keeping gathered
    weights alive — the full rest (embeddings + head) is never
    co-resident with the layer scan.
    """
    from distributeddataparallel_tpu.models.transformer import (
        DecoderBlock,
        rope_frequencies,
    )
    from distributeddataparallel_tpu.parallel.pipeline_parallel import (
        _embed,
        _head,
    )

    block = DecoderBlock(cfg)
    rest_tmpl = meta.gather_template(meta.rest_template, gather_dtype)
    layer_tmpl = meta.gather_template(meta.layer_template, gather_dtype)
    gdt = gather_dtype or jnp.float32

    def gather_rest(flat_rest):
        vec = lax.all_gather(
            flat_rest.astype(gdt), data_axis, axis=0, tiled=True
        )
        return unflatten(vec, rest_tmpl)

    def embed_part(flat_rest, inputs):
        return _embed(cfg, gather_rest(flat_rest), inputs)

    def head_part(flat_rest, x):
        return _head(cfg, gather_rest(flat_rest), x)

    if cfg.remat:
        embed_part = jax.checkpoint(embed_part, prevent_cse=False)
        head_part = jax.checkpoint(head_part, prevent_cse=False)

    def forward(flat, inputs):
        from distributeddataparallel_tpu.parallel.pipeline_parallel import (
            _check_seq_bound,
        )

        _check_seq_bound(cfg, inputs.shape[1])
        rope = (
            rope_frequencies(
                cfg.dims_per_head, cfg.max_seq_len, theta=cfg.rope_theta
            )
            if cfg.positional == "rope"
            else None
        )

        def body(x, layer_row):
            vec = lax.all_gather(
                layer_row.astype(gdt), data_axis, axis=0, tiled=True
            )
            lp = unflatten(vec, layer_tmpl)
            y = block.apply({"params": lp["block"]}, x, None, rope, True)
            return y, None

        if cfg.remat:
            body = jax.checkpoint(body, prevent_cse=False)
        x = embed_part(flat["rest"], inputs)
        x, _ = lax.scan(body, x, flat["layers"])
        return head_part(flat["rest"], x)

    return forward


def make_fsdp_train_step(
    cfg,
    *,
    mesh: Mesh,
    data_axis: str = "data",
    tp_axis: str | None = None,
    donate: bool = True,
    grad_clip: float | None = None,
    accum_steps: int = 1,
    gather_dtype=None,
):
    """Compiled FSDP train step for a scanned TransformerLM config.

    ``step(state, batch, rng) -> (state, metrics)`` with
    ``batch = {"tokens": (B_local, S+1) int32}`` sharded over
    ``data_axis`` and ``state`` from ``fsdp_state``.  Per layer, the
    forward gathers 1/N-sharded weights, computes, and discards; the
    backward re-gathers (``cfg.remat``) and reduce-scatters gradients —
    both directions emerge from AD of the all_gather, no hooks anywhere.

    ``tp_axis``: FSDP x Megatron — state from ``fsdp_state(...,
    tp_axis=)``, cfg with ``tp_axis`` set.  Gathers stay on the data
    axis (each model position reconstitutes its own TP shard); the
    block's conjugate operators complete replicated-param grads across
    the model axis, so the psum_scatter from AD remains the only
    data-axis sync.

    ``gather_dtype`` (e.g. ``jnp.bfloat16``): cast the f32 master shards
    to this dtype BEFORE the all_gather — halves collective bytes and
    gathered-weight residency; norm scales ride in the lower precision
    (torch-FSDP's ``param_dtype`` mixed-precision trade).  Grads still
    land f32 on the master flats.

    ``accum_steps`` accumulates microbatch gradients IN THE SHARDED
    layout (each microbatch's reduce-scatter lands on the 1/N flats and
    sums there) — like torch FSDP under no_sync, every microbatch still
    re-gathers the weights; only the optimizer step is amortized.
    """
    if accum_steps < 1:
        raise ValueError(f"accum_steps must be >= 1, got {accum_steps}")
    if (tp_axis is None) != (cfg.tp_axis is None):
        raise ValueError("pass tp_axis to BOTH the config and the factory")
    from distributeddataparallel_tpu.ops.losses import lm_cross_entropy

    n = mesh.shape[data_axis]
    n_tp = mesh.shape[tp_axis] if tp_axis is not None else 1
    meta = _Meta(cfg, n, tp_axis, n_tp)
    forward = _forward_pieces(
        cfg, meta, data_axis=data_axis, gather_dtype=gather_dtype
    )

    def _replica_step(state, batch, rng):
        toks = batch["tokens"]
        inputs, targets = toks[:, :-1], toks[:, 1:]
        S = inputs.shape[1]

        def loss_fn(flat, inputs, targets):
            return lm_cross_entropy(forward(flat, inputs), targets)

        if accum_steps == 1:
            loss, gflat = jax.value_and_grad(loss_fn)(
                state.params, inputs, targets
            )
        else:
            if inputs.shape[0] % accum_steps:
                raise ValueError(
                    f"per-replica batch {inputs.shape[0]} not divisible "
                    f"by accum_steps={accum_steps}"
                )
            mb = inputs.shape[0] // accum_steps
            mbs_in = inputs.reshape(accum_steps, mb, S)
            mbs_tgt = targets.reshape(accum_steps, mb, S)

            def acc_body(carry, xs):
                acc_g, acc_l = carry
                i, t = xs
                l, g = jax.value_and_grad(loss_fn)(state.params, i, t)
                return (
                    jax.tree.map(jnp.add, acc_g, g), acc_l + l
                ), None

            # Grad shapes ARE the flat param shapes (no eval_shape trace).
            zeros = jax.tree.map(
                lambda p: jnp.zeros(p.shape, p.dtype), state.params
            )
            (gflat, loss), _ = lax.scan(
                acc_body, (zeros, jnp.zeros((), jnp.float32)),
                (mbs_in, mbs_tgt),
            )
            inv = 1.0 / accum_steps
            gflat = jax.tree.map(lambda g: g * inv, gflat)
            loss = loss * inv
        # The all_gather transpose SUMMED per-replica contributions into
        # each shard; divide for DDP mean semantics (global loss is the
        # mean of per-replica means).  Cast: under gather_dtype the
        # cotangents arrive in that dtype; the master update is f32.
        gflat = jax.tree.map(
            lambda g, p: g.astype(p.dtype) / n, gflat, state.params
        )
        if grad_clip is not None:
            from distributeddataparallel_tpu.parallel.data_parallel import (
                clip_scale,
                sumsq_f32,
            )

            if meta.n_tp == 1:
                # The flat shards partition the gradient vector: global
                # norm² is one psum of local sum-of-squares — exact.
                gnorm = jnp.sqrt(lax.psum(sumsq_f32(gflat), data_axis))
            else:
                # FSDP x TP: each model position's flats hold its
                # Megatron shard for sharded leaves but a FULL copy of
                # replicated leaves (and of the whole rest flat), so a
                # plain psum over (data, tp) would count those n_tp
                # times.  flat_chunk_sumsq de-weights duplicated
                # elements (the ONE implementation of this numerics,
                # shared with the ZeRO clip); every layer row shares the
                # same leaf layout, so one vmap covers the stack.  The
                # zero pad tail is weight-agnostic.
                from distributeddataparallel_tpu.parallel.data_parallel import (
                    flat_chunk_sumsq,
                )

                flat = jax.tree_util.tree_flatten_with_path(
                    meta.layer_template
                )[0]
                sizes = [int(np.prod(leaf.shape)) for _, leaf in flat]
                dups = [
                    # stacked-view ndim (+1 for the leading L) — the
                    # same rule flatten_full slices with.
                    1 if meta._model_dim(
                        _path_names(path), leaf.ndim + 1
                    ) is not None else meta.n_tp
                    for path, leaf in flat
                ]
                start = lax.axis_index(data_axis) * meta.layer_chunk
                s = jnp.sum(jax.vmap(
                    lambda row: flat_chunk_sumsq(row, start, sizes, dups)
                )(gflat["layers"])) + sumsq_f32(gflat["rest"]) / meta.n_tp
                s = lax.psum(s, data_axis)
                s = lax.psum(s, tp_axis)
                gnorm = jnp.sqrt(s)
            gflat = jax.tree.map(
                lambda g: g * clip_scale(gnorm, grad_clip), gflat
            )
        new_state = state.apply_gradients(gflat)
        return new_state, {"loss": lax.pmean(loss, data_axis)}

    compiled = None
    jit_kwargs = {"donate_argnums": (0,)} if donate else {}

    def step(state, batch, rng):
        nonlocal compiled
        if compiled is None:
            opt_specs = jax.tree.map(
                lambda l: meta.flat_leaf_spec(l, data_axis),
                state.opt_state,
            )
            specs = state.replace(
                step=P(),
                params=meta.param_specs(data_axis),
                opt_state=opt_specs,
                model_state={},
            )
            sharded = jax.shard_map(
                _replica_step,
                mesh=mesh,
                in_specs=(specs, P(data_axis), P()),
                out_specs=(specs, P()),
                check_vma=False,
            )
            compiled = jax.jit(sharded, **jit_kwargs)
            step.jitted = compiled
        return compiled(state, batch, rng)

    step.jitted = None

    # Expected-collective manifest for the graph linter: FSDP's step is
    # all_gather(params) + reduce_scatter(grads) over the data axis
    # (plus activation psums over the TP axis when two-level).  The f32
    # master flats make f32 reduction the design, not a promotion bug.
    from distributeddataparallel_tpu.analysis.rules import (
        collective_manifest,
    )

    _reduce = {data_axis: {"all_gather": (1, None),
                           "reduce_scatter": (1, None),
                           "psum": (0, None)}}
    if tp_axis is not None:
        _reduce[tp_axis] = {"psum": (0, None), "all_gather": (0, None),
                            "reduce_scatter": (0, None)}
    step.collective_manifest = collective_manifest(
        "fsdp",
        grad_reduce=_reduce,
        donate=donate,
        allow_f32_reduce=True,
    )
    return step


def make_fsdp_eval_step(
    cfg,
    *,
    mesh: Mesh,
    data_axis: str = "data",
    tp_axis: str | None = None,
    gather_dtype=None,
):
    """Streaming masked evaluation over the sharded flats.

    ``eval_step(params_flat, batch) -> (metrics, count)`` with the same
    contract as ``make_eval_step(masked=True)``: ``batch = {"tokens":
    (B_local, S+1), "valid": (B_local,)}``, per-row metrics weighted by
    the valid mask, count = global valid rows.  The forward is the
    training step's (per-layer gathers, short-liveness rest) — the full
    replicated tree that ``fsdp_gather_params`` would materialize never
    exists, which is what makes ``--fsdp --eval`` viable at 8B
    (ADVICE r2: the gathered-eval path silently capped FSDP at small
    models).
    """
    from distributeddataparallel_tpu.ops.losses import (
        per_example_accuracy,
        per_example_cross_entropy,
    )

    n = mesh.shape[data_axis]
    n_tp = mesh.shape[tp_axis] if tp_axis is not None else 1
    meta = _Meta(cfg, n, tp_axis, n_tp)
    forward = _forward_pieces(
        cfg, meta, data_axis=data_axis, gather_dtype=gather_dtype
    )

    def _eval(flat, batch):
        toks, valid = batch["tokens"], batch["valid"]
        inputs, targets = toks[:, :-1], toks[:, 1:]
        logits = forward(flat, inputs)
        v = valid.astype(jnp.float32)
        loss_sum = jnp.sum(per_example_cross_entropy(logits, targets) * v)
        acc_sum = jnp.sum(per_example_accuracy(logits, targets) * v)
        cnt = jnp.sum(v)
        loss_sum, acc_sum, cnt = (
            lax.psum(x, data_axis) for x in (loss_sum, acc_sum, cnt)
        )
        denom = jnp.maximum(cnt, 1.0)
        return {"loss": loss_sum / denom, "accuracy": acc_sum / denom}, cnt

    compiled = None

    def eval_step(params_flat, batch):
        nonlocal compiled
        if compiled is None:
            sharded = jax.shard_map(
                _eval,
                mesh=mesh,
                in_specs=(
                    meta.param_specs(data_axis),
                    {"tokens": P(data_axis), "valid": P(data_axis)},
                ),
                out_specs=(P(), P()),
                check_vma=False,
            )
            compiled = jax.jit(sharded)
        return compiled(params_flat, batch)

    return eval_step
