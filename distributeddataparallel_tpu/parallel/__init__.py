from distributeddataparallel_tpu.parallel.sampler import DistributedSampler  # noqa: F401
from distributeddataparallel_tpu.parallel.data_parallel import (  # noqa: F401
    DataParallel,
    all_reduce_gradients,
    broadcast_params,
    bucket_gradients,
)
