from distributeddataparallel_tpu.parallel.sampler import DistributedSampler  # noqa: F401
from distributeddataparallel_tpu.parallel.data_parallel import (  # noqa: F401
    DataParallel,
    all_reduce_gradients,
    broadcast_params,
    bucket_gradients,
)
from distributeddataparallel_tpu.parallel.context_parallel import (  # noqa: F401
    cp_positions,
    make_cp_eval_step,
    make_cp_train_step,
    ring_attention,
    ulysses_attention,
)
from distributeddataparallel_tpu.parallel.overlap import (  # noqa: F401
    OVERLAP_COMPILER_OPTIONS,
    cpu_fabric_note,
    grad_sync_schedule_evidence,
    grad_sync_schedule_pair,
    overlap_compiler_options,
    schedule_report,
)
from distributeddataparallel_tpu.parallel.powersgd import (  # noqa: F401
    powersgd_state,
    powersgd_state_specs,
    powersgd_sync,
    powersgd_wire_bytes,
)
from distributeddataparallel_tpu.parallel.zero import zero_state  # noqa: F401
from distributeddataparallel_tpu.parallel.tensor_parallel import (  # noqa: F401
    copy_to_tp,
    reduce_from_tp,
    shard_state_tp,
    tp_param_specs,
    tp_state_specs,
)
from distributeddataparallel_tpu.parallel.pipeline_parallel import (  # noqa: F401
    make_pp_eval_step,
    make_pp_train_step,
    pp_param_specs,
    pp_state_specs,
    shard_state_pp,
)
from distributeddataparallel_tpu.parallel.expert_parallel import (  # noqa: F401
    ep_param_specs,
    ep_state_specs,
    shard_state_ep,
)
from distributeddataparallel_tpu.parallel.fsdp import (  # noqa: F401
    fsdp_gather_params,
    fsdp_state,
    make_fsdp_eval_step,
    make_fsdp_train_step,
)
