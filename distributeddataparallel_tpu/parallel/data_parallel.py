"""Data-parallel gradient synchronization: the TPU-native DDP core.

What ``DDP(model, device_ids=[rank])`` (ref dpp.py:39) does imperatively —
broadcast initial params, hook autograd, bucket gradients into 25 MiB
groups, all-reduce each bucket asynchronously overlapped with backward,
divide by world size — falls out declaratively in SPMD JAX:

- *param broadcast*  → ``broadcast_params``: replicate across the mesh
  (and across hosts from process 0, the exact analog of DDP's rank-0
  broadcast).
- *grad hooks + all-reduce* → ``all_reduce_gradients``: ``lax.pmean`` over
  the ``data`` mesh axis inside the jit'd step; XLA's latency-hiding
  scheduler overlaps the collective with remaining backward compute (the
  performance property SURVEY.md §3.4 calls out as THE thing to reproduce).
- *bucketing* → ``bucket_gradients``: optional explicit 25 MiB-style
  coalescing of gradient leaves into a few large all-reduces.  Stock XLA
  usually makes this unnecessary; it exists for parity with BASELINE
  config 4 ("bucketed psum all-reduce") and as a measured fallback.
- *no_sync / grad accumulation* → handled in ``training.train_step`` by
  accumulating microbatch grads locally and reducing once per boundary.

All reduction helpers are designed to run **inside** ``shard_map`` (they
reference a named mesh axis).
"""

from __future__ import annotations

from typing import Any, Sequence

import jax
import jax.numpy as jnp
from jax import lax
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

Pytree = Any

#: DDP's default bucket size: 25 MiB (SURVEY.md §2b, torch Reducer default).
DEFAULT_BUCKET_BYTES = 25 * 1024 * 1024

#: Overlap (chain) mode bucket size: unlike DDP's 25 MiB (NCCL latency
#: amortization), the TPU async-collective scheduler overlaps best when
#: large leaves ride solo as native-dtype all-reduces; only sub-MiB
#: leaves (biases, norms) are worth coalescing.  Measured in
#: parallel/overlap.py — 25 MiB concat buckets get zero async windows.
OVERLAP_BUCKET_BYTES = 1 * 1024 * 1024


def _check_compress(compress: str | None) -> None:
    if compress not in (None, "bf16"):
        raise ValueError(f"compress must be None or 'bf16', got {compress!r}")


def all_reduce_gradients(
    grads: Pytree,
    axis_name: str = "data",
    *,
    op: str = "mean",
    bucket_bytes: int | None = None,
    chain: bool = False,
    compress: str | None = None,
) -> Pytree:
    """All-reduce a gradient pytree across the data axis (inside shard_map).

    ``op='mean'`` reproduces DDP's divide-by-world-size so every replica
    holds averaged gradients and stays in lockstep under a local optimizer
    step (ref dpp.py:52-53 semantics).  ``chain=True`` (needs
    ``bucket_bytes``) orders the buckets with barriers so the compiler
    keeps them separate and can overlap them with backward — see
    ``bucket_gradients`` and ``parallel.overlap``.

    ``compress='bf16'`` is the comm-hook analog of torch DDP's
    ``bf16_compress_hook`` (the stack behind ref dpp.py:52's
    ``register_comm_hook`` surface): gradients cross the wire in
    bfloat16 — half the bytes of f32 — and are cast back to each leaf's
    dtype after the reduce.  bf16 keeps f32's exponent range, so unlike
    the fp16 hook no loss-scaling is needed; replicas remain in lockstep
    because every replica sees the SAME compressed-then-averaged value.
    """
    if op not in ("mean", "sum"):
        raise ValueError(f"op must be 'mean' or 'sum', got {op!r}")
    _check_compress(compress)
    if chain and bucket_bytes is None:
        bucket_bytes = OVERLAP_BUCKET_BYTES
    if bucket_bytes is not None:
        return bucket_gradients(
            grads, axis_name, op=op, bucket_bytes=bucket_bytes, chain=chain,
            compress=compress,
        )
    red = lax.pmean if op == "mean" else lax.psum

    def _leaf(g):
        if compress == "bf16" and g.dtype == jnp.float32:
            return red(g.astype(jnp.bfloat16), axis_name).astype(g.dtype)
        return red(g, axis_name)

    return jax.tree.map(_leaf, grads)


def bucket_gradients(
    grads: Pytree,
    axis_name: str = "data",
    *,
    op: str = "mean",
    bucket_bytes: int = DEFAULT_BUCKET_BYTES,
    chain: bool = False,
    compress: str | None = None,
) -> Pytree:
    """Coalesced all-reduce: flatten grad leaves into ~bucket_bytes groups,
    reduce each group as one flat vector, scatter back.

    The explicit analog of DDP's Reducer bucketing (25 MiB default).  Like
    DDP, buckets are formed in *reverse* leaf order so the bucket containing
    the last-computed (earliest-layer) grads is reduced last — giving the
    XLA scheduler the same freedom to overlap early buckets with remaining
    backward work.

    ``chain=True`` additionally threads an ``optimization_barrier`` from
    each bucket's reduced output into the next bucket's input.  That
    pins the reduction order (reverse, like DDP's Reducer stream) and —
    the real point — makes the buckets *data-dependent* on each other so
    XLA's all-reduce combiner cannot legally merge them back into one
    giant all-reduce that waits for the entire backward.  Separate
    buckets are what lets the TPU backend's async-collective-fusion +
    latency-hiding scheduler start bucket k's all-reduce while the
    remaining backward is still computing (see ``parallel.overlap`` for
    the scheduled-HLO evidence).  Numerics are identical to the unchained
    path; the barrier moves no data.
    """
    from distributeddataparallel_tpu import native

    _check_compress(compress)
    leaves, treedef = jax.tree.flatten(grads)
    # Reverse-order ~bucket_bytes grouping, planned by the native layer
    # (the role DDP gives its C++ Reducer); runs at trace time.
    buckets = native.plan_buckets(
        [l.size * l.dtype.itemsize for l in leaves], bucket_bytes
    )

    reduced: list[Any] = [None] * len(leaves)
    prev = None
    # Static mean divisor: lax.psum(1, axis) would materialize a scalar
    # all-reduce per bucket on the TPU backend, serializing the tail of
    # the overlapped schedule; the axis size is known at trace time.
    inv_n = 1.0 / lax.axis_size(axis_name)
    for bucket in buckets:
        # chain (overlap) mode reduces in the native gradient dtype (DDP
        # semantics, half the wire bytes for bf16) when the bucket is
        # dtype-uniform; the legacy coalescing path keeps its original
        # f32 accumulation so --bucket-mb numerics are unchanged.
        dtypes = {leaves[i].dtype for i in bucket}
        bdt = (
            dtypes.pop()
            if chain and len(dtypes) == 1
            else jnp.float32
        )
        if compress == "bf16" and all(
            leaves[i].dtype == jnp.float32 for i in bucket
        ):
            # bf16 comm-hook (torch bf16_compress_hook semantics:
            # compress -> average -> decompress), f32 buckets only — the
            # same predicate the unbucketed leaf path applies.  A bucket
            # holding sub-f32 leaves (bf16/fp16 grads) must not take a
            # second precision hit, and an f64 leaf must not silently
            # drop 45 mantissa bits on the wire.
            bdt = jnp.bfloat16
        if len(bucket) == 1:
            # Single-leaf bucket: skip the concat/flatten round-trip —
            # keeps the leaf's layout intact for the async scheduler.
            flat = leaves[bucket[0]].astype(bdt)
        else:
            flat = jnp.concatenate(
                [leaves[i].reshape(-1).astype(bdt) for i in bucket]
            )
        if chain and prev is not None:
            # Bucket k may not start reducing until bucket k-1 finished:
            # the combiner would have to create a cycle to merge them.
            flat, prev = lax.optimization_barrier((flat, prev))
        flat = lax.psum(flat, axis_name)
        if chain:
            prev = flat
        if op == "mean":
            flat = flat * jnp.asarray(inv_n, bdt)
        if len(bucket) == 1:
            i = bucket[0]
            reduced[i] = flat.astype(leaves[i].dtype)
            continue
        offset = 0
        for i in bucket:
            n = leaves[i].size
            reduced[i] = (
                flat[offset : offset + n]
                .reshape(leaves[i].shape)
                .astype(leaves[i].dtype)
            )
            offset += n
    return jax.tree.unflatten(treedef, reduced)


def sync_grad_in_backward(
    x: Pytree,
    axis_name: str,
    *,
    op: str = "mean",
    compress: str | None = None,
):
    """Identity on the forward; all-reduces the COTANGENT over
    ``axis_name`` on the backward.

    Applied to a parameter *use site* inside a ``lax.scan`` body (the
    scanned transformer block reads its per-layer param slice through
    this, ``models.transformer grad_sync_axis``), the gradient of that
    slice is reduced INSIDE the backward scan iteration — which is the
    only place a scanned model's layer grads exist before the loop
    stacks them.  Measured on the scanned-Llama v5e:2x4 schedule: the
    post-loop reduction of the stacked grads cannot overlap anything
    (2.3% of compute in windows); the in-body reduction runs one async
    window per scan trip while that trip's remaining backward computes
    (OVERLAP.md).  The train step must then SKIP these leaves in its own
    sync (``make_train_step(presynced=...)``) — re-reducing an averaged
    gradient is numerically a no-op but pays the full wire bytes twice.

    Forward-only applies (eval, decode) never touch the axis, so the
    model stays usable outside ``shard_map``.

    ``compress='bf16'``: the cotangent crosses the wire in bfloat16 (the
    in-scan-body arm of the bf16 comm hook — see
    ``all_reduce_gradients``).
    """
    _check_compress(compress)

    @jax.custom_vjp
    def ident(t):
        return t

    def fwd(t):
        return t, None

    def bwd(_, g):
        red = lax.pmean if op == "mean" else lax.psum
        if compress == "bf16" and g.dtype == jnp.float32:
            return (red(g.astype(jnp.bfloat16), axis_name).astype(g.dtype),)
        return (red(g, axis_name),)

    ident.defvjp(fwd, bwd)
    return jax.tree.map(ident, x)


def sumsq_f32(tree: Pytree):
    """Sum of squares of every leaf, accumulated in float32 (bf16 grads
    would lose the norm to ~8 mantissa bits) — the building block for
    global-norm clipping in every layout (replicated, ZeRO chunks, FSDP
    flats: sharded layouts psum this across their axis, which is exact
    because the shards partition the gradient vector)."""
    import jax.numpy as jnp

    return sum(
        jnp.sum(l.astype(jnp.float32) ** 2) for l in jax.tree.leaves(tree)
    )


def spec_axes(spec) -> tuple:
    """Mesh axis names a PartitionSpec shards over (flattened, deduped)."""
    axes = []
    for part in tuple(spec):
        if part is None:
            continue
        for ax in (part if isinstance(part, tuple) else (part,)):
            if ax is not None and ax not in axes:
                axes.append(ax)
    return tuple(axes)


def model_axes_sumsq(grads: Pytree, specs: Pytree):
    """Exact global gradient sum-of-squares under model-axis sharding
    (inside shard_map) — the global-norm-clip building block for
    TP/EP/PP layouts.

    Per leaf: the local shard's f32 sumsq, psum'd over every mesh axis
    the leaf's PartitionSpec shards it on.  Leaves replicated over an
    axis are identical there (the conjugate custom-VJP ops complete
    their grads per position), so no psum — adding them once per
    position is the de-duplication.  The total is identical on every
    mesh position, which is what makes a uniform clip scale safe.
    """
    gl = jax.tree.leaves(grads)
    sl = jax.tree.leaves(specs, is_leaf=lambda x: isinstance(x, P))
    if len(gl) != len(sl):
        raise ValueError(
            f"grads/specs leaf count mismatch: {len(gl)} vs {len(sl)}"
        )
    total = jnp.zeros((), jnp.float32)
    for g, sp in zip(gl, sl):
        s = jnp.sum(g.astype(jnp.float32) ** 2)
        for ax in spec_axes(sp):
            s = lax.psum(s, ax)
        total = total + s
    return total


def flat_chunk_sumsq(
    chunk,
    chunk_start,
    leaf_sizes: Sequence[int],
    leaf_dup: Sequence[int],
):
    """Sum-of-squares of one flat-layout gradient chunk with duplicate
    de-weighting — the ZeRO/FSDP-side counterpart of
    ``model_axes_sumsq``.

    The flat vector concatenates leaves (``leaf_sizes`` elements each,
    then zero padding); ``leaf_dup[i]`` is how many model-axis positions
    hold an identical copy of leaf i (1 = sharded/unique).  Elements of
    duplicated leaves contribute ``x²/dup`` so that the subsequent psum
    over the model axes counts them exactly once.  ``chunk_start`` may
    be traced (``axis_index * chunk``).
    """
    x2 = chunk.astype(jnp.float32) ** 2
    pos = chunk_start + jnp.arange(chunk.shape[0])
    w = jnp.ones_like(x2)
    off = 0
    for size, dup in zip(leaf_sizes, leaf_dup):
        if dup != 1:
            w = jnp.where(
                (pos >= off) & (pos < off + size), 1.0 / dup, w
            )
        off += size
    return jnp.sum(x2 * w)


def clip_scale(gnorm, clip_norm: float):
    """min(1, clip/norm): the torch clip_grad_norm_ scale factor — ONE
    definition (epsilon included) shared by the replicated, ZeRO, and
    FSDP clip paths."""
    import jax.numpy as jnp

    return jnp.minimum(1.0, clip_norm / (gnorm + 1e-12))


def broadcast_params(params: Pytree, mesh: Mesh) -> Pytree:
    """Replicate params across every device of the mesh.

    The analog of DDP's construction-time broadcast of rank-0 parameters
    (SURVEY.md §2b "Gradient synchronization" (i)).  Within one process this
    is a replicated ``device_put``; across processes, values from process 0
    are broadcast to all so every host starts from identical weights even if
    their host-side RNG diverged.
    """
    if jax.process_count() > 1:
        from jax.experimental import multihost_utils

        params = multihost_utils.broadcast_one_to_all(params)
    return jax.device_put(params, NamedSharding(mesh, P()))


class DataParallel:
    """Object-style facade over the mesh, mirroring the DDP wrapper's role.

    Where the reference writes::

        model = DDP(model, device_ids=[rank])          # ref dpp.py:39

    this framework writes::

        dp = DataParallel(mesh)                        # or DataParallel()
        params = dp.replicate(params)                  # DDP ctor broadcast
        step = make_train_step(loss_fn, opt, mesh=dp.mesh)
        batch = dp.shard_batch(batch)                  # data -> 'data' axis

    It owns no gradient machinery itself — synchronization lives inside the
    compiled step — but centralizes mesh construction, replication, and
    batch sharding so user code never touches device objects (the analog of
    ``.to(rank)`` at ref dpp.py:38,48 disappearing).
    """

    def __init__(
        self,
        mesh: Mesh | None = None,
        *,
        axis_name: str = "data",
        devices: Sequence[jax.Device] | None = None,
    ):
        if mesh is None:
            from distributeddataparallel_tpu.runtime.distributed import make_mesh

            mesh = make_mesh((axis_name,), devices=devices)
        if axis_name not in mesh.axis_names:
            raise ValueError(
                f"axis {axis_name!r} not in mesh axes {mesh.axis_names}"
            )
        self.mesh = mesh
        self.axis_name = axis_name

    @property
    def num_replicas(self) -> int:
        return self.mesh.shape[self.axis_name]

    def replicate(self, tree: Pytree) -> Pytree:
        return broadcast_params(tree, self.mesh)

    def shard_batch(self, batch: Pytree) -> Pytree:
        """Place a host batch sharded along the data axis (single impl in
        ``data.loader.shard_batch``: sharded device_put on one host,
        per-process global-array assembly multi-host)."""
        from distributeddataparallel_tpu.data.loader import shard_batch

        return shard_batch(batch, self.mesh, self.axis_name)


def masked_tree_mean(
    metrics: Pytree,
    mask: jnp.ndarray,
    axis_name: str,
    seq_axis: str | None = None,
):
    """Global masked mean of per-row metric trees: ``(means, count)``.

    ``metrics`` leaves are per-row vectors on this shard; ``mask`` is the
    matching (rows,) validity mask (0 on sampler-padded duplicate rows).
    With ``seq_axis`` set (DP×CP), per-row values are first pmean'd over
    the sequence axis — chunks are equal-length, so that is the exact
    global per-row mean — before the masked reduction over ``axis_name``.
    The single implementation keeps DP and DP×CP eval semantics from
    drifting (used by ``make_eval_step`` / ``make_cp_eval_step``).
    """
    mask = mask.astype(jnp.float32)
    den = lax.psum(jnp.sum(mask), axis_name)

    def _mean(v):
        v = v.astype(jnp.float32)
        if seq_axis is not None:
            v = lax.pmean(v, seq_axis)
        num = lax.psum(jnp.sum(v * mask), axis_name)
        return num / jnp.maximum(den, 1.0)

    return jax.tree.map(_mean, metrics), den
