"""PowerSGD gradient compression: DDP's low-rank comm hook, TPU-native.

The torch stack behind ref dpp.py:52 ships
``torch.distributed.algorithms.ddp_comm_hooks.powerSGD_hook`` (Vogels et
al., NeurIPS 2019): instead of all-reducing the full gradient matrix
``M (n x m)``, workers all-reduce the rank-``r`` factors of one power
iteration and feed the approximation error back into the next step's
gradient.  Wire bytes per matrix drop from ``n*m`` to ``(n+m)*r`` —
for this repo's GPT-2 124M tied embedding that is 154 MB -> 1.6 MB at
rank 4, i.e. the exposed all-reduce tail (OVERLAP.md §4/§6) essentially
vanishes; what stays dense is the 1-D leaves (biases/norms, ~0.1% of
the payload).

Per step and per 2-D-reshapeable leaf (others stay dense all-reduce):

1. ``M += err``          (error feedback, per-replica local)
2. ``P = M @ Q``         (Q warm-started across steps, m x r)
3. ``P = mean_allreduce(P); P = orth(P)``   (thin QR)
4. ``Q = M^T @ P``
5. ``Q = mean_allreduce(Q)``
6. ``M_hat = P @ Q^T``   (identical on every replica -> lockstep params)
7. ``err = M - M_hat``   (stored for the next step)

Replicas stay in lockstep because the applied update is built only from
all-reduced quantities; the residual ``err`` is intentionally
per-replica (the hook's defining trick — local error accumulates until
the low-rank basis rotates enough to express it).  With
``rank >= min(n, m)`` the projector spans the full column space and the
hook reproduces the dense all-reduce up to float error — the exactness
pin ``tests/test_powersgd.py`` uses.

State lives in ``TrainState.comm_state`` (created by
``powersgd_state``), threaded through the compiled step like optimizer
moments and checkpointed with it.  SPMD layout: ``q`` is replicated
(it is all-reduced every step); ``err`` carries a leading
data-axis-sized dim sharded ``P(axis)`` — each replica owns exactly its
row, which is the honest representation of per-replica divergence
(a "replicated" err would lie to the compiler and checkpoint garbage).
"""

from __future__ import annotations

from typing import Any

import flax.struct
import jax
import jax.numpy as jnp
from jax import lax
from jax.sharding import PartitionSpec as P

Pytree = Any


@flax.struct.dataclass
class PowerSGDLeaf:
    """Hook state for one compressed leaf: the warm-started factor and
    the per-replica error residual (leading dim = data-axis size, row i
    owned by replica i).  A typed node so spec/state traversals can
    distinguish it from the param tree's own nested dicts."""

    q: jax.Array
    err: jax.Array


def _is_entry(x) -> bool:
    return x is None or isinstance(x, PowerSGDLeaf)

#: Leaves with fewer elements than this stay dense even when 2-D: at
#: tiny sizes the two factor all-reduces cost more launches than the
#: payload saves (torch's hook has the same escape hatch via
#: min_compression_rate).
MIN_COMPRESS_ELEMS = 16384


def _matrix_shape(leaf) -> tuple[int, int] | None:
    """The (n, m) 2-D view PowerSGD compresses, or None for
    dense-all-reduce leaves (rank < 2 or too small).  ND leaves fold the
    LEADING dims and keep the last: flax convs are HWIO, so torch's
    ``view(shape[0], -1)`` would pin n to the 3-tall spatial dim and cap
    the approximation rank at 3; folding to ``(H*W*I, O)`` preserves the
    channel structure the low-rank basis actually lives in."""
    if leaf.ndim < 2 or leaf.size < MIN_COMPRESS_ELEMS:
        return None
    m = leaf.shape[-1]
    return (leaf.size // m, m)


def _leaf_rank(nm: tuple[int, int], rank: int) -> int:
    """Per-leaf effective rank: thin QR caps the basis at min(n, m),
    so an oversized requested rank would otherwise create a q whose
    shape SHRINKS after the first sync — breaking donated-buffer shape
    stability and checkpoint templates."""
    return min(rank, *nm)


def powersgd_state(
    params: Pytree,
    n_data: int,
    rank: int = 4,
    *,
    seed: int = 0,
    mesh=None,
    axis_name: str = "data",
) -> Pytree:
    """Per-leaf hook state: ``PowerSGDLeaf(q=(m, min(rank, n, m)),
    err=(n_data, *leaf.shape))`` for compressed leaves, ``None`` for
    dense ones.

    ``n_data`` is the data-axis size; err row i is replica i's residual
    (shard with ``powersgd_state_specs``).  Q is warm-started with the
    SAME seeded gaussian on every replica (fold_in over the leaf index),
    so replicas agree from step 0 without a broadcast.  Pass ``mesh`` to
    allocate each residual DIRECTLY in its sharded layout (P(axis_name)
    on the leading dim) — without it the zeros materialize on the
    default device first, an n_data x param-bytes transient.
    """
    if rank < 1:
        raise ValueError(f"rank must be >= 1, got {rank}")
    if n_data < 1:
        raise ValueError(f"n_data must be >= 1, got {n_data}")
    from jax.sharding import NamedSharding

    err_dev = q_dev = None
    if mesh is not None:
        err_dev = NamedSharding(mesh, P(axis_name))
        q_dev = NamedSharding(mesh, P())
    flat, treedef = jax.tree.flatten(params)
    key = jax.random.PRNGKey(seed)
    out = []
    for i, leaf in enumerate(flat):
        nm = _matrix_shape(leaf)
        if nm is None:
            out.append(None)
            continue
        _, m = nm
        q = jax.random.normal(
            jax.random.fold_in(key, i), (m, _leaf_rank(nm, rank)),
            jnp.float32,
        )
        if q_dev is not None:
            q = jax.device_put(q, q_dev)
        out.append(
            PowerSGDLeaf(
                q=q,
                err=jnp.zeros(
                    (n_data, *leaf.shape), leaf.dtype, device=err_dev
                ),
            )
        )
    return jax.tree.unflatten(treedef, out)


def powersgd_state_specs(comm_state: Pytree, axis_name: str = "data"):
    """PartitionSpec tree for ``powersgd_state``: q replicated, err
    sharded on its leading (replica) dim."""

    def _entry(s):
        if s is None:
            return None
        return PowerSGDLeaf(q=P(), err=P(axis_name))

    return jax.tree.map(_entry, comm_state, is_leaf=_is_entry)


def _orthonormalize(p):
    """Thin-QR orthonormal basis of P's columns (r is small; QR on TPU
    lowers to a custom call).  f32 throughout."""
    q, _ = jnp.linalg.qr(p.astype(jnp.float32))
    return q


def powersgd_sync(
    grads: Pytree,
    hook_state: Pytree,
    axis_name: str = "data",
    *,
    op: str = "mean",
) -> tuple[Pytree, Pytree]:
    """One PowerSGD round over the data axis (inside shard_map, where
    each err leaf arrives as its local ``(1, *leaf.shape)`` row).

    Returns ``(synced_grads, new_hook_state)``.  Compressed leaves carry
    the rank-r approximation of the replica-mean gradient (identical on
    every replica); dense leaves are plain pmean/psum.  ``op="sum"``
    scales the approximation by the axis size after the mean round —
    summing P and Q separately would NOT approximate the summed matrix.
    """
    if op not in ("mean", "sum"):
        raise ValueError(f"op must be 'mean' or 'sum', got {op!r}")
    n_axis = lax.axis_size(axis_name)

    flat_g, treedef = jax.tree.flatten(grads)
    flat_s = treedef.flatten_up_to(hook_state)
    out_g, out_s = [], []
    for g, s in zip(flat_g, flat_s):
        if s is None:
            red = lax.pmean if op == "mean" else lax.psum
            out_g.append(red(g, axis_name))
            out_s.append(None)
            continue
        n, m = _matrix_shape(g)
        mat = (g + s.err[0].astype(g.dtype)).reshape(n, m)
        mat32 = mat.astype(jnp.float32)
        p = lax.pmean(mat32 @ s.q, axis_name)
        p = _orthonormalize(p)
        q = lax.pmean(mat32.T @ p, axis_name)
        m_hat32 = p @ q.T
        m_hat = m_hat32.astype(g.dtype)
        err = (mat - m_hat).reshape(g.shape)[None]
        if op == "sum":
            m_hat = m_hat * jnp.asarray(n_axis, m_hat.dtype)
        out_g.append(m_hat.reshape(g.shape))
        out_s.append(PowerSGDLeaf(q=q, err=err))
    return (
        jax.tree.unflatten(treedef, out_g),
        jax.tree.unflatten(treedef, out_s),
    )


def powersgd_wire_bytes(params: Pytree, rank: int = 4) -> dict:
    """Wire-byte ledger: dense vs PowerSGD factors (f32 wire) — the
    compression the bench/docs report, computed exactly from shapes."""
    dense = comp = 0
    n_compressed = n_dense = 0
    for leaf in jax.tree.leaves(params):
        nbytes = leaf.size * leaf.dtype.itemsize
        nm = _matrix_shape(leaf)
        if nm is None:
            dense += nbytes
            comp += nbytes
            n_dense += 1
        else:
            n, m = nm
            r = _leaf_rank(nm, rank)
            dense += nbytes
            comp += 4 * r * (n + m)  # P round + Q round, f32
            n_compressed += 1
    return {
        "rank": rank,
        "dense_wire_bytes": dense,
        "powersgd_wire_bytes": comp,
        "compression_ratio": round(dense / comp, 1) if comp else None,
        "n_compressed_leaves": n_compressed,
        "n_dense_leaves": n_dense,
    }
