"""Expert parallelism: MoE expert sharding over an ``expert`` mesh axis.

Thin layout layer over the same conjugate-operator machinery as tensor
parallelism (``parallel.tensor_parallel``): ``models.transformer.MoEMLP``
enters the expert region through ``copy_to_tp`` and combines with
``reduce_from_tp``, so every replicated parameter's gradient (router,
attention, norms, embeddings) comes out complete on all positions and
the data-axis sync needs no EP-awareness.  This module supplies the
parameter layout: expert weight stacks shard their EXPERT dim, which is
the leading dim unscanned and the second dim under scanned layers —
expressed by right-aligning the rule against each leaf.
"""

from __future__ import annotations

import functools
from typing import Any

import jax
from jax import lax
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

Pytree = Any


# --- Token sharding conjugate pair (token-choice dispatch) ---------------
#
# The token-choice MoE path splits a REPLICATED token buffer 1/n per
# expert-axis position, exchanges slots with all_to_all, and must hand
# back a replicated buffer.  Under the replicated-compute convention the
# cotangent arriving at the exit is already identical on every position,
# so the naive pair (slice with zero-pad transpose + all_gather with
# psum_scatter transpose) would overcount upstream gradients n× — the
# correct conjugates are slice<->all_gather with NO reduction:

@functools.partial(jax.custom_vjp, nondiff_argnums=(1,))
def ep_shard_tokens(x, axis_name: str):
    """Forward: this position's 1/n slice along dim 0 of a replicated
    buffer.  Backward: all_gather of the per-position cotangents —
    upstream replicated-param grads come out complete AND identical on
    all positions (no psum; each position contributes exactly its
    chunk)."""
    n = lax.psum(1, axis_name)
    r = lax.axis_index(axis_name)
    size = x.shape[0] // n
    return lax.dynamic_slice_in_dim(x, r * size, size, 0)


def _shard_fwd(x, axis_name):
    return ep_shard_tokens(x, axis_name), None


def _shard_bwd(axis_name, _, g):
    return (lax.all_gather(g, axis_name, tiled=True),)


ep_shard_tokens.defvjp(_shard_fwd, _shard_bwd)


@functools.partial(jax.custom_vjp, nondiff_argnums=(1,))
def ep_unshard_tokens(x, axis_name: str):
    """Forward: all_gather the per-position chunks back to the
    replicated buffer.  Backward: each position keeps its own chunk of
    the (replicated-identical) cotangent — a psum_scatter here would
    multiply by n."""
    return lax.all_gather(x, axis_name, tiled=True)


def _unshard_fwd(x, axis_name):
    return ep_unshard_tokens(x, axis_name), None


def _unshard_bwd(axis_name, _, g):
    n = lax.psum(1, axis_name)
    r = lax.axis_index(axis_name)
    size = g.shape[0] // n
    return (lax.dynamic_slice_in_dim(g, r * size, size, 0),)


ep_unshard_tokens.defvjp(_unshard_fwd, _unshard_bwd)

#: path-suffix -> partition of the TRAILING dims (right-aligned).
_EP_RULES: tuple[tuple[tuple[str, ...], tuple[str | None, ...]], ...] = (
    (("experts_up",), ("expert", None, None)),    # (E, d, f)
    (("experts_gate",), ("expert", None, None)),
    (("experts_down",), ("expert", None, None)),  # (E, f, d)
)


def _spec_for_path(path, leaf, axis_name: str) -> P:
    for suffix, dims in _EP_RULES:
        if path[-len(suffix):] == suffix:
            trailing = tuple(
                axis_name if d == "expert" else None for d in dims
            )
            pad = leaf.ndim - len(trailing)
            if pad < 0:
                raise ValueError(
                    f"param {'/'.join(path)} has rank {leaf.ndim}, "
                    f"expected >= {len(trailing)}"
                )
            return P(*((None,) * pad + trailing))
    return P()


def ep_param_specs(tree: Pytree, axis_name: str = "expert") -> Pytree:
    """PartitionSpec tree sharding expert stacks over ``axis_name``;
    works on optimizer state too (optax trees embed the param paths)."""
    flat = jax.tree_util.tree_flatten_with_path(tree)[0]
    treedef = jax.tree.structure(tree)
    specs = []
    for path, leaf in flat:
        names = tuple(
            str(getattr(k, "key", getattr(k, "name", k))) for k in path
        )
        specs.append(_spec_for_path(names, leaf, axis_name))
    return jax.tree.unflatten(treedef, specs)


def ep_state_specs(state, axis_name: str = "expert") -> Pytree:
    return state.replace(
        step=P(),
        params=ep_param_specs(state.params, axis_name),
        opt_state=ep_param_specs(state.opt_state, axis_name),
        model_state=jax.tree.map(lambda _: P(), state.model_state),
    )


def check_ep_divisibility(params: Pytree, mesh: Mesh, axis_name: str) -> None:
    """Clear error when the expert-axis size does not divide an expert
    stack — shared by every EP-aware placement (plain EP and PP x EP)."""
    n = mesh.shape[axis_name]
    for path, leaf in jax.tree_util.tree_flatten_with_path(params)[0]:
        names = tuple(str(getattr(k, "key", k)) for k in path)
        spec = _spec_for_path(names, leaf, axis_name)
        for dim, name in enumerate(spec):
            if name == axis_name and leaf.shape[dim] % n:
                raise ValueError(
                    f"EP degree {n} does not divide dim {dim} of param "
                    f"{'/'.join(names)} (shape {leaf.shape}) — "
                    f"moe_experts must be divisible by the expert-axis size"
                )


def shard_state_ep(state, mesh: Mesh, axis_name: str = "expert"):
    """Place a full TrainState with expert stacks sharded over the expert
    axis (the EP analog of ``broadcast_params``)."""
    check_ep_divisibility(state.params, mesh, axis_name)
    return jax.tree.map(
        lambda x, s: jax.device_put(x, NamedSharding(mesh, s)),
        state,
        ep_state_specs(state, axis_name),
    )


def model_axes_param_specs(
    params: Pytree,
    tp_axis: str | None = None,
    ep_axis: str | None = None,
) -> Pytree:
    """Combined per-leaf specs for the model-sharding axes: Megatron TP
    rules and expert EP rules hit disjoint leaves, so each leaf takes
    whichever rule is non-trivial (replicated when neither applies).
    THE single source for train-step in_specs, state placement, and eval
    in_specs — keep them from diverging."""
    from distributeddataparallel_tpu.parallel.tensor_parallel import (
        tp_param_specs,
    )

    specs = (
        tp_param_specs(params, tp_axis)
        if tp_axis is not None
        else jax.tree.map(lambda _: P(), params)
    )
    if ep_axis is not None:
        specs = jax.tree.map(
            lambda t, e: e if any(e) else t,
            specs,
            ep_param_specs(params, ep_axis),
        )
    return specs


def model_axes_state_specs(
    state, tp_axis: str | None = None, ep_axis: str | None = None
) -> Pytree:
    return state.replace(
        step=P(),
        params=model_axes_param_specs(state.params, tp_axis, ep_axis),
        opt_state=model_axes_param_specs(state.opt_state, tp_axis, ep_axis),
        model_state=jax.tree.map(lambda _: P(), state.model_state),
    )


def shard_state_model_axes(
    state,
    mesh: Mesh,
    tp_axis: str | None = None,
    ep_axis: str | None = None,
):
    """Place a full TrainState under any combination of TP and EP."""
    return jax.tree.map(
        lambda x, s: jax.device_put(x, NamedSharding(mesh, s)),
        state,
        model_axes_state_specs(state, tp_axis, ep_axis),
    )
