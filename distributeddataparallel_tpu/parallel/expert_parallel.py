"""Expert parallelism: MoE expert sharding over an ``expert`` mesh axis.

Thin layout layer over the same conjugate-operator machinery as tensor
parallelism (``parallel.tensor_parallel``): ``models.transformer.MoEMLP``
enters the expert region through ``copy_to_tp`` and combines with
``reduce_from_tp``, so every replicated parameter's gradient (router,
attention, norms, embeddings) comes out complete on all positions and
the data-axis sync needs no EP-awareness.  This module supplies the
parameter layout: expert weight stacks shard their EXPERT dim, which is
the leading dim unscanned and the second dim under scanned layers —
expressed by right-aligning the rule against each leaf.
"""

from __future__ import annotations

import functools
from typing import Any

import jax
from jax import lax
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

Pytree = Any


# --- Token sharding conjugate pair (token-choice dispatch) ---------------
#
# The token-choice MoE path splits a REPLICATED token buffer 1/n per
# expert-axis position, exchanges slots with all_to_all, and must hand
# back a replicated buffer.  Under the replicated-compute convention the
# cotangent arriving at the exit is already identical on every position,
# so the naive pair (slice with zero-pad transpose + all_gather with
# psum_scatter transpose) would overcount upstream gradients n× — the
# correct conjugates are slice<->all_gather with NO reduction:

@functools.partial(jax.custom_vjp, nondiff_argnums=(1,))
def ep_shard_tokens(x, axis_name: str):
    """Forward: this position's 1/n slice along dim 0 of a replicated
    buffer.  Backward: all_gather of the per-position cotangents —
    upstream replicated-param grads come out complete AND identical on
    all positions (no psum; each position contributes exactly its
    chunk)."""
    n = lax.psum(1, axis_name)
    r = lax.axis_index(axis_name)
    size = x.shape[0] // n
    return lax.dynamic_slice_in_dim(x, r * size, size, 0)


def _shard_fwd(x, axis_name):
    return ep_shard_tokens(x, axis_name), None


def _shard_bwd(axis_name, _, g):
    return (lax.all_gather(g, axis_name, tiled=True),)


ep_shard_tokens.defvjp(_shard_fwd, _shard_bwd)


@functools.partial(jax.custom_vjp, nondiff_argnums=(1,))
def ep_unshard_tokens(x, axis_name: str):
    """Forward: all_gather the per-position chunks back to the
    replicated buffer.  Backward: each position keeps its own chunk of
    the (replicated-identical) cotangent — a psum_scatter here would
    multiply by n."""
    return lax.all_gather(x, axis_name, tiled=True)


def _unshard_fwd(x, axis_name):
    return ep_unshard_tokens(x, axis_name), None


def _unshard_bwd(axis_name, _, g):
    n = lax.psum(1, axis_name)
    r = lax.axis_index(axis_name)
    size = g.shape[0] // n
    return (lax.dynamic_slice_in_dim(g, r * size, size, 0),)


ep_unshard_tokens.defvjp(_unshard_fwd, _unshard_bwd)

#: path-suffix -> partition of the TRAILING dims (right-aligned).
_EP_RULES: tuple[tuple[tuple[str, ...], tuple[str | None, ...]], ...] = (
    (("experts_up",), ("expert", None, None)),    # (E, d, f)
    (("experts_gate",), ("expert", None, None)),
    (("experts_down",), ("expert", None, None)),  # (E, f, d)
)


def _spec_for_path(path, leaf, axis_name: str) -> P:
    for suffix, dims in _EP_RULES:
        if path[-len(suffix):] == suffix:
            trailing = tuple(
                axis_name if d == "expert" else None for d in dims
            )
            pad = leaf.ndim - len(trailing)
            if pad < 0:
                raise ValueError(
                    f"param {'/'.join(path)} has rank {leaf.ndim}, "
                    f"expected >= {len(trailing)}"
                )
            return P(*((None,) * pad + trailing))
    return P()


def ep_param_specs(tree: Pytree, axis_name: str = "expert") -> Pytree:
    """PartitionSpec tree sharding expert stacks over ``axis_name``;
    works on optimizer state too (optax trees embed the param paths)."""
    flat = jax.tree_util.tree_flatten_with_path(tree)[0]
    treedef = jax.tree.structure(tree)
    specs = []
    for path, leaf in flat:
        names = tuple(
            str(getattr(k, "key", getattr(k, "name", k))) for k in path
        )
        specs.append(_spec_for_path(names, leaf, axis_name))
    return jax.tree.unflatten(treedef, specs)


def ep_state_specs(state, axis_name: str = "expert") -> Pytree:
    return state.replace(
        step=P(),
        params=ep_param_specs(state.params, axis_name),
        opt_state=ep_param_specs(state.opt_state, axis_name),
        model_state=jax.tree.map(lambda _: P(), state.model_state),
    )


def check_ep_divisibility(params: Pytree, mesh: Mesh, axis_name: str) -> None:
    """Clear error when the expert-axis size does not divide an expert
    stack — shared by every EP-aware placement (plain EP and PP x EP)."""
    n = mesh.shape[axis_name]
    for path, leaf in jax.tree_util.tree_flatten_with_path(params)[0]:
        names = tuple(str(getattr(k, "key", k)) for k in path)
        spec = _spec_for_path(names, leaf, axis_name)
        for dim, name in enumerate(spec):
            if name == axis_name and leaf.shape[dim] % n:
                raise ValueError(
                    f"EP degree {n} does not divide dim {dim} of param "
                    f"{'/'.join(names)} (shape {leaf.shape}) — "
                    f"moe_experts must be divisible by the expert-axis size"
                )


def shard_state_ep(state, mesh: Mesh, axis_name: str = "expert"):
    """Place a full TrainState with expert stacks sharded over the expert
    axis (the EP analog of ``broadcast_params``)."""
    check_ep_divisibility(state.params, mesh, axis_name)
    return jax.tree.map(
        lambda x, s: jax.device_put(x, NamedSharding(mesh, s)),
        state,
        ep_state_specs(state, axis_name),
    )


def model_axes_param_specs(
    params: Pytree,
    tp_axis: str | None = None,
    ep_axis: str | None = None,
) -> Pytree:
    """Combined per-leaf specs for the model-sharding axes: Megatron TP
    rules and expert EP rules hit disjoint leaves, so each leaf takes
    whichever rule is non-trivial (replicated when neither applies).
    THE single source for train-step in_specs, state placement, and eval
    in_specs — keep them from diverging."""
    from distributeddataparallel_tpu.parallel.tensor_parallel import (
        tp_param_specs,
    )

    specs = (
        tp_param_specs(params, tp_axis)
        if tp_axis is not None
        else jax.tree.map(lambda _: P(), params)
    )
    if ep_axis is not None:
        specs = jax.tree.map(
            lambda t, e: e if any(e) else t,
            specs,
            ep_param_specs(params, ep_axis),
        )
    return specs


def model_axes_state_specs(
    state, tp_axis: str | None = None, ep_axis: str | None = None
) -> Pytree:
    return state.replace(
        step=P(),
        params=model_axes_param_specs(state.params, tp_axis, ep_axis),
        opt_state=model_axes_param_specs(state.opt_state, tp_axis, ep_axis),
        model_state=jax.tree.map(lambda _: P(), state.model_state),
    )


def shard_state_model_axes(
    state,
    mesh: Mesh,
    tp_axis: str | None = None,
    ep_axis: str | None = None,
):
    """Place a full TrainState under any combination of TP and EP."""
    return jax.tree.map(
        lambda x, s: jax.device_put(x, NamedSharding(mesh, s)),
        state,
        model_axes_state_specs(state, tp_axis, ep_axis),
    )


# --- Measured EP evidence (VERDICT r4 weak 6) ----------------------------


def ep_memory_evidence(
    *,
    topology: str = "v5e:2x4",
    experts: int = 16,
    num_layers: int = 6,
    d_model: int = 512,
    d_ff: int = 2048,
    seq_len: int = 512,
    global_batch: int = 8,
) -> dict:
    """MEASURE — not roofline-argue — that EP shards the expert weights
    away, by AOT-compiling the REAL token-choice MoE train step twice for
    a multi-chip TPU topology and reading the executables' per-chip
    memory analysis:

    - ``dp``: experts replicated (plain DP over all chips) — per-chip
      argument bytes carry the FULL expert stack;
    - ``ep``: experts sharded over an ``expert`` axis spanning all chips
      (``make_train_step(..., ep_axis=...)`` → the same
      ``model_axes_state_specs`` layout production uses) — per-chip
      argument bytes carry ``1/ep_degree`` of it.

    The round-4 bench showed the e16/e4 throughput ratio lands ON the
    per-chip weight-traffic roofline, i.e. the only E-dependent cost is
    per-chip expert weight bytes; this closes the loop by measuring that
    EP makes those bytes ``total/ep_degree`` per chip, so at fixed
    experts-per-chip the roofline — and therefore throughput — is
    E-independent.  Both compiles go through ``step.lower`` on the real
    step (no proxy model).  Raises on a missing TPU compiler — callers
    decide how to degrade.
    """
    import dataclasses

    import jax
    import jax.numpy as jnp
    import optax

    from distributeddataparallel_tpu.models.transformer import (
        TransformerLM,
        gpt2_124m,
    )
    from distributeddataparallel_tpu.ops import lm_cross_entropy
    from distributeddataparallel_tpu.parallel.overlap import (
        compiler_stamp,
        tpu_topology_mesh,
    )
    from distributeddataparallel_tpu.training.state import TrainState
    from distributeddataparallel_tpu.training.train_step import (
        make_train_step,
    )

    mesh_dp = tpu_topology_mesh(topology, ("data",))
    n = mesh_dp.devices.size
    mesh_ep = tpu_topology_mesh(
        topology, ("data", "expert"), shape=(1, n)
    )
    if experts % n:
        raise ValueError(f"experts={experts} not divisible by chips={n}")

    cfg_ep = gpt2_124m(
        num_layers=num_layers, d_model=d_model, d_ff=d_ff, num_heads=8,
        vocab_size=8192, max_seq_len=seq_len, dtype=jnp.bfloat16,
        moe_experts=experts, moe_top_k=2, moe_capacity_factor=1.25,
        ep_axis="expert",
    )
    cfg_dp = dataclasses.replace(cfg_ep, ep_axis=None)

    def make_state(cfg):
        model = TransformerLM(dataclasses.replace(cfg, ep_axis=None))
        params = model.init(
            jax.random.PRNGKey(0), jnp.zeros((1, seq_len), jnp.int32)
        )["params"]
        return TrainState.create(
            apply_fn=None, params=params, tx=optax.sgd(0.01)
        )

    state_sds = jax.eval_shape(lambda: make_state(cfg_ep))
    batch_sds = {
        "tokens": jax.ShapeDtypeStruct(
            (global_batch, seq_len + 1), jnp.int32
        )
    }
    rng_sds = jax.ShapeDtypeStruct((2,), jnp.uint32)

    # Analytic split of the parameter tree: a leaf is an expert stack iff
    # the production EP spec rule shards it — the SAME rule the step's
    # in_specs use, so this classification cannot drift from the layout.
    specs = ep_param_specs(state_sds.params, "expert")
    expert_bytes = nonexpert_bytes = 0
    for leaf, spec in zip(
        jax.tree.leaves(state_sds.params),
        jax.tree.leaves(specs, is_leaf=lambda s: isinstance(s, P)),
    ):
        nbytes = leaf.size * leaf.dtype.itemsize
        if any(ax is not None for ax in spec):
            expert_bytes += nbytes
        else:
            nonexpert_bytes += nbytes
    batch_bytes = (seq_len + 1) * global_batch * 4

    def compile_bytes(cfg, mesh, ep_axis):
        model = TransformerLM(cfg)

        def loss_fn(params, b, rng):
            toks = b["tokens"]
            logits = model.apply({"params": params}, toks[:, :-1])
            return lm_cross_entropy(logits, toks[:, 1:]), {}

        step = make_train_step(loss_fn, mesh=mesh, ep_axis=ep_axis)
        comp = step.lower(state_sds, batch_sds, rng_sds).compile()
        ma = comp.memory_analysis()
        out = {
            "argument_bytes_per_chip": int(ma.argument_size_in_bytes),
            "temp_bytes_per_chip": int(ma.temp_size_in_bytes),
        }
        try:  # record the executable's actual expert-leaf placement
            in_shard = comp.input_shardings[0][0]
            ex = next(
                s
                for s, sp in zip(
                    jax.tree.leaves(in_shard.params),
                    jax.tree.leaves(
                        specs, is_leaf=lambda x: isinstance(x, P)
                    ),
                )
                if any(ax is not None for ax in sp)
            )
            out["expert_leaf_sharding"] = str(ex)
        # ddplint: allow[broad-except] — best-effort diagnostics field only
        except Exception:
            pass
        return out

    ep = compile_bytes(cfg_ep, mesh_ep, "expert")
    dp = compile_bytes(cfg_dp, mesh_dp, None)

    # Expected per-chip argument bytes.  dp: full params + 1/n of the
    # batch.  ep: data axis is size 1 (batch replicated across expert
    # positions) + full non-expert params + expert stacks / n.
    exp_dp = expert_bytes + nonexpert_bytes + batch_bytes // n + 8
    exp_ep = expert_bytes // n + nonexpert_bytes + batch_bytes + 8
    meas_shard_frac = (
        dp["argument_bytes_per_chip"] - ep["argument_bytes_per_chip"]
    ) / expert_bytes
    rep = {
        "topology": topology,
        "n_chips": n,
        "experts": experts,
        "ep_degree": n,
        "experts_per_chip": experts // n,
        "expert_param_bytes_total": expert_bytes,
        "nonexpert_param_bytes": nonexpert_bytes,
        "dp_replicated": {**dp, "expected_argument_bytes": exp_dp},
        "ep_sharded": {**ep, "expected_argument_bytes": exp_ep},
        # (dp - ep) args / expert bytes: 1 - 1/n when EP shards exactly
        # the expert stacks and nothing else (batch replication under
        # the size-1 data axis costs batch_bytes*(1-1/n) extra on the ep
        # side — folded into the expectations above, negligible here).
        "measured_expert_shard_frac": round(meas_shard_frac, 4),
        "expected_expert_shard_frac": round(1.0 - 1.0 / n, 4),
        "per_chip_expert_bytes_ep": expert_bytes // n,
        "claim": (
            f"per-chip expert weight bytes under EP-{n} at E={experts} "
            f"== E={experts // n} single-chip: the weight-traffic "
            "roofline (the bench's measured residual E-dependence) is "
            "E-independent at fixed experts-per-chip"
        ),
        "compiler": compiler_stamp(),
    }
    for side, exp in (("dp_replicated", exp_dp), ("ep_sharded", exp_ep)):
        got = rep[side]["argument_bytes_per_chip"]
        rep[side]["match_err"] = round(abs(got - exp) / exp, 4)
    return rep
