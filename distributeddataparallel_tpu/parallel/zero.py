"""ZeRO-1-style cross-replica weight-update sharding (TPU-native).

The reference trains pure-DP with fully replicated optimizer state
(`optim.SGD`, ref dpp.py:41) — every rank redundantly stores and updates
identical state.  For the Llama-3 8B config (BASELINE 5) that redundancy
is what breaks the per-chip memory budget (SURVEY.md §7 hard-part 3), and
the TPU-native fix is the cross-replica weight-update sharding of
arXiv 2004.13336 (the XLA-side ZeRO-1, referenced from PAPERS.md):

    grads --reduce_scatter--> 1/N grad shard per replica
          --optimizer update on the shard (opt state lives sharded)
          --all_gather--> full updated params on every replica

Same math as DDP+optimizer (identical updates, bitwise modulo reduction
order), ~same communication volume as one all-reduce (reduce_scatter +
all_gather = all_reduce's two phases), but optimizer state memory drops
N×: per chip, Adam on 8B goes from ~64 GB of f32 (mu+nu) to ~8 GB on an
8-way axis.

Mechanics: parameters/grads are flattened into one f32 vector padded to a
multiple of the axis size; each replica owns one contiguous chunk.  The
optimizer transform runs on that flat chunk — valid for elementwise
transforms (sgd, momentum, adam, adamw's decoupled decay).  Transforms
needing *global* tensor structure (clip_by_global_norm across the full
tree) would see only the local chunk; compose those upstream of the
train step or use replicated DP instead.

Used through ``training.train_step.make_train_step(..., zero=True)`` with
a state built by ``zero_state(...)``.

ZeRO-2/3 extension (arXiv 2004.13336's full weight-update sharding):
``zero_state(..., level=2/3)`` + ``make_train_step(..., zero=2/3)``.
Both levels move to a BUCKETED flat layout (``bucket_plan``): leaves are
grouped into ~bucket_bytes buckets (reverse leaf order, the
gradient-ready order ``native.plan_buckets`` emits), each bucket padded
to a multiple of the axis size, and a device's shard is the
concatenation of its per-bucket sub-chunks.  Bucketing is what lets the
reduce-scatter start before the last grad exists and the all-gather
interleave with tail-of-step compute (the ``parallel/overlap`` latency
story), instead of one monolithic vector serializing the wire behind
the slowest leaf.

  level 2: grads leave backward via per-bucket ``psum_scatter`` into the
      1/N shard — the full *reduced* f32 gradient vector is never
      materialized (only a bucket-sized staging concat plus the shard);
      update runs on the shard; params re-replicate via per-bucket
      ``all_gather``.
  level 3: params STAY sharded between steps (``Zero3Params`` holds just
      the flat f32 shard + static layout meta); each step all-gathers
      them bucketwise inside the differentiated function, so AD's
      transpose of the gather IS the reduce-scatter of the grads and the
      update consumes the shard directly — no replicated param tree ever
      lives in the state, only the transient gathered values inside the
      step.

``moment_dtype=`` stores optimizer moments low-bit between steps
(``low_bit_moments``): bf16 or blockwise-int8, written back each step
with stochastic rounding (``ops/quant``) so the round-trip is unbiased.
"""

from __future__ import annotations

import dataclasses
from typing import Any, NamedTuple

import flax.struct
import jax
import jax.numpy as jnp
import numpy as np
import optax
from jax import lax
from jax.sharding import Mesh, PartitionSpec as P

Pytree = Any

#: Default bucket granularity for the zero2/zero3 flat layout — matches
#: the overlap machinery's bucket size so the scatter/gather stream has
#: the same latency-hiding shape as the bucketed-overlap dp path.
ZERO_BUCKET_BYTES = 1 << 20


def flat_size(params: Pytree, num_shards: int) -> tuple[int, int]:
    """(padded_total, chunk): total f32 elements padded to num_shards."""
    total = sum(leaf.size for leaf in jax.tree.leaves(params))
    chunk = -(-total // num_shards)
    return chunk * num_shards, chunk


def flatten_f32(params: Pytree, padded: int, cast: str = "f32") -> jnp.ndarray:
    """Concat all leaves into one padded flat vector.

    ``cast`` makes the dtype policy explicit instead of silently
    upcasting whatever arrives:

    - ``"f32"`` (default): every leaf is upcast to f32 — the master-copy
      convention of the ZeRO update path, where the flat vector IS the
      f32 master and ``unflatten`` casts back per leaf.
    - ``"preserve"``: keep the tree's own (uniform) dtype — for bf16
      master-param configs that want the flat vector in bf16 too.  A
      MIXED-dtype tree raises: concatenating would silently promote the
      narrow leaves, which is exactly the bug this flag exists to stop.
    - ``"strict"``: raise unless every leaf is already f32 — for callers
      that want proof no hidden upcast (and its 2x memory) happened.
    """
    leaves = jax.tree.leaves(params)
    dtypes = {jnp.dtype(l.dtype) for l in leaves}
    if cast == "f32":
        flat = jnp.concatenate(
            [l.reshape(-1).astype(jnp.float32) for l in leaves]
        )
    elif cast == "preserve":
        if len(dtypes) > 1:
            raise TypeError(
                "flatten_f32(cast='preserve'): tree mixes dtypes "
                f"{sorted(str(d) for d in dtypes)}; concatenation would "
                "silently promote — cast the tree to one dtype first or "
                "use cast='f32' for an explicit f32 master"
            )
        flat = jnp.concatenate([l.reshape(-1) for l in leaves])
    elif cast == "strict":
        bad = dtypes - {jnp.dtype(jnp.float32)}
        if bad:
            raise TypeError(
                "flatten_f32(cast='strict'): non-f32 leaves present "
                f"({sorted(str(d) for d in bad)}); pass cast='f32' to "
                "upcast explicitly or cast='preserve' for a uniform "
                "non-f32 master"
            )
        flat = jnp.concatenate([l.reshape(-1) for l in leaves])
    else:
        raise ValueError(
            f"flatten_f32: unknown cast={cast!r} "
            "(want 'f32', 'preserve', or 'strict')"
        )
    return jnp.pad(flat, (0, padded - flat.shape[0]))


def unflatten(flat: jnp.ndarray, like: Pytree) -> Pytree:
    """Inverse of flatten_f32: split `flat` back into `like`'s structure,
    casting each leaf to its original dtype."""
    leaves, treedef = jax.tree.flatten(like)
    out, offset = [], 0
    for leaf in leaves:
        n = leaf.size
        out.append(
            flat[offset : offset + n].reshape(leaf.shape).astype(leaf.dtype)
        )
        offset += n
    return jax.tree.unflatten(treedef, out)


# ---------------------------------------------------------------------------
# Bucketed flat layout (zero2/zero3)
# ---------------------------------------------------------------------------


@dataclasses.dataclass(frozen=True)
class BucketPlan:
    """The static bucketed-flat layout shared by every zero2/zero3 site
    (scatter, update, gather, opt-state init).  ``buckets`` holds leaf
    indices per bucket in reduction order; ``padded`` is each bucket's
    flat length padded to a multiple of the axis size; ``sub`` is the
    per-position sub-chunk (``padded[b] // num_shards``); ``local`` is
    one position's total shard length (``sum(sub)``).  Frozen tuples so
    the plan can ride static (hashable) through jit/shard_map."""

    buckets: tuple[tuple[int, ...], ...]
    padded: tuple[int, ...]
    sub: tuple[int, ...]
    local: int

    @property
    def n_buckets(self) -> int:
        return len(self.buckets)


def bucket_plan(
    params: Pytree, num_shards: int, bucket_bytes: int | None = None
) -> BucketPlan:
    """Plan the bucketed flat layout for ``params`` over ``num_shards``.

    Reuses ``native.plan_buckets`` (reverse leaf order — the order grads
    become ready in backward) on the f32-master byte sizes; each bucket
    pads independently to the axis size so every position owns an equal
    sub-chunk of every bucket.  Works on concrete arrays or
    ShapeDtypeStructs (only ``.size`` is read), so mesh-sim can plan on
    abstract params."""
    from distributeddataparallel_tpu import native

    leaves = jax.tree.leaves(params)
    groups = native.plan_buckets(
        [leaf.size * 4 for leaf in leaves], bucket_bytes or ZERO_BUCKET_BYTES
    )
    buckets, padded, sub = [], [], []
    for idxs in groups:
        size = sum(leaves[i].size for i in idxs)
        pad = -(-size // num_shards) * num_shards
        buckets.append(tuple(idxs))
        padded.append(pad)
        sub.append(pad // num_shards)
    return BucketPlan(
        buckets=tuple(buckets),
        padded=tuple(padded),
        sub=tuple(sub),
        local=sum(sub),
    )


def _flatten_bucket(leaves: list, idxs: tuple[int, ...], padded_b: int):
    flat = jnp.concatenate(
        [leaves[i].reshape(-1).astype(jnp.float32) for i in idxs]
    )
    return jnp.pad(flat, (0, padded_b - flat.shape[0]))


def scatter_grads_bucketed(
    grads: Pytree, plan: BucketPlan, axis_name: str, num_shards: int
):
    """Local per-leaf grads -> this position's reduce-scattered flat
    shard (mean over the axis).  Each bucket goes through its own
    ``psum_scatter``, so only a bucket-sized f32 staging concat plus the
    growing 1/N shard are live past the reduction — the full *reduced*
    gradient vector never exists (the ZeRO-2 memory claim), and the
    per-bucket collectives can overlap the rest of backward."""
    leaves = jax.tree.leaves(grads)
    subs = [
        lax.psum_scatter(
            _flatten_bucket(leaves, idxs, padded_b),
            axis_name,
            scatter_dimension=0,
            tiled=True,
        )
        for idxs, padded_b in zip(plan.buckets, plan.padded)
    ]
    return jnp.concatenate(subs) / num_shards


def shard_params_bucketed(params: Pytree, plan: BucketPlan, axis_name: str):
    """Local view of (replicated) params -> this position's flat f32
    shard in the bucketed layout.  The layout twin of
    ``scatter_grads_bucketed`` — element i of the result is the param
    for element i of the scattered grad shard."""
    leaves = jax.tree.leaves(params)
    idx = lax.axis_index(axis_name)
    subs = [
        lax.dynamic_slice(
            _flatten_bucket(leaves, idxs, padded_b), (idx * sub_b,), (sub_b,)
        )
        for idxs, padded_b, sub_b in zip(plan.buckets, plan.padded, plan.sub)
    ]
    return jnp.concatenate(subs)


def gather_params_bucketed(
    flat_shard, like: Pytree, plan: BucketPlan, axis_name: str
) -> Pytree:
    """This position's flat shard -> the full param tree, one
    ``all_gather`` per bucket (static slice offsets, so the unflatten is
    free at runtime).  Differentiable: AD's transpose of the gather is a
    per-bucket ``psum_scatter`` of the cotangents — which is exactly how
    zero3 gets its grads reduce-scattered without writing that code."""
    leaves, treedef = jax.tree.flatten(like)
    out: list = [None] * len(leaves)
    off = 0
    for idxs, sub_b in zip(plan.buckets, plan.sub):
        full = lax.all_gather(
            flat_shard[off : off + sub_b], axis_name, axis=0, tiled=True
        )
        o = 0
        for i in idxs:
            leaf = leaves[i]
            out[i] = (
                full[o : o + leaf.size]
                .reshape(leaf.shape)
                .astype(leaf.dtype)
            )
            o += leaf.size
        off += sub_b
    return jax.tree.unflatten(treedef, out)


# ---------------------------------------------------------------------------
# ZeRO-3 sharded-param state
# ---------------------------------------------------------------------------


@dataclasses.dataclass(frozen=True)
class Zero3Meta:
    """Static (hashable) layout metadata for a zero3 flat param shard:
    everything needed to rebuild the structured tree from the flat
    vector.  Rides as a non-pytree field of ``Zero3Params`` so it is
    part of the jit/shard_map static signature, not a traced value."""

    treedef: Any
    shapes: tuple[tuple[int, ...], ...]
    dtypes: tuple[str, ...]
    plan: BucketPlan
    num_shards: int

    def like(self) -> Pytree:
        """The structured tree as ShapeDtypeStructs (shape/dtype only —
        all the gather needs)."""
        return jax.tree.unflatten(
            self.treedef,
            [
                jax.ShapeDtypeStruct(s, jnp.dtype(d))
                for s, d in zip(self.shapes, self.dtypes)
            ],
        )


@flax.struct.dataclass
class Zero3Params:
    """What ``TrainState.params`` holds at zero3: the flat f32 master
    shard (global shape ``(num_shards * plan.local,)``, sharded
    ``P(axis)``) plus the static layout meta.  The structured tree only
    exists transiently inside the step (bucketwise gather)."""

    flat: jax.Array
    meta: Zero3Meta = flax.struct.field(pytree_node=False)


def zero3_meta(params: Pytree, num_shards: int, plan: BucketPlan) -> Zero3Meta:
    leaves, treedef = jax.tree.flatten(params)
    return Zero3Meta(
        treedef=treedef,
        shapes=tuple(tuple(l.shape) for l in leaves),
        dtypes=tuple(str(jnp.dtype(l.dtype)) for l in leaves),
        plan=plan,
        num_shards=num_shards,
    )


def zero3_gather(flat_shard, meta: Zero3Meta, axis_name: str) -> Pytree:
    """Local flat shard -> full structured params (inside shard_map).
    THE zero3 forward entry: trace this inside the differentiated
    function so its transpose reduce-scatters the grads."""
    return gather_params_bucketed(flat_shard, meta.like(), meta.plan, axis_name)


def zero3_gather_params(state, mesh: Mesh, axis_name: str = "data") -> Pytree:
    """Host-side helper: materialize the full (replicated) param tree
    from a zero3 TrainState — for eval, export, or a dp-layout
    checkpoint.  Costs one full param gather; don't call it per step."""
    meta = state.params.meta
    fn = jax.jit(
        jax.shard_map(
            lambda f: zero3_gather(f, meta, axis_name),
            mesh=mesh,
            in_specs=(P(axis_name),),
            out_specs=jax.tree.map(lambda _: P(), meta.like()),
            check_vma=False,
        )
    )
    return fn(state.params.flat)


# ---------------------------------------------------------------------------
# Low-bit optimizer moments
# ---------------------------------------------------------------------------


class LowBitMomentState(NamedTuple):
    """Wrapper state: the inner tx's state with large float vectors held
    compressed, plus the PRNG key that drives the stochastic-rounding
    writeback."""

    inner: Any
    key: jax.Array


def low_bit_moments(
    tx: optax.GradientTransformation,
    moment_dtype: str | None,
    *,
    seed: int = 0,
    min_size: int = 256,
) -> optax.GradientTransformation:
    """Store ``tx``'s moment vectors in ``moment_dtype`` between steps.

    Each step: decompress -> inner ``tx.update`` in f32 -> recompress
    with STOCHASTIC rounding (``ops/quant``), so the quantization error
    enters the moment EMA as zero-mean noise rather than a systematic
    truncation bias — the error compensation that keeps low-bit Adam
    converging.  ``moment_dtype``:

    - ``None``/``"f32"``: returns ``tx`` unchanged.
    - ``"bf16"``: float vectors >= ``min_size`` elements kept as bf16
      (2 bytes/param/moment).
    - ``"int8"``: kept as blockwise-absmax int8 + per-block f32 scales
      (~1 byte/param/moment; ``ops.quant.MOMENT_BLOCK`` block length).

    Scalars and small leaves (bias-correction counts, etc.) stay f32.
    Key threading is data-independent, so identical keys across mesh
    positions are fine — each position quantizes different elements.
    """
    if moment_dtype in (None, "f32", "float32"):
        return tx
    if moment_dtype not in ("bf16", "bfloat16", "int8"):
        raise ValueError(
            f"low_bit_moments: moment_dtype={moment_dtype!r} "
            "(want None/'f32', 'bf16', or 'int8')"
        )
    from distributeddataparallel_tpu.ops.quant import (
        Q8Moment,
        dequantize_moment,
        quantize_moment_int8,
        stochastic_round_bf16,
    )

    to_int8 = moment_dtype == "int8"

    def _compressible(leaf) -> bool:
        return (
            not isinstance(leaf, Q8Moment)
            and hasattr(leaf, "dtype")
            and jnp.issubdtype(leaf.dtype, jnp.floating)
            and getattr(leaf, "ndim", 0) == 1
            and leaf.size >= min_size
        )

    def _compress(tree, key):
        leaves, treedef = jax.tree.flatten(tree)
        out = []
        for i, leaf in enumerate(leaves):
            if _compressible(leaf):
                k = jax.random.fold_in(key, i)
                out.append(
                    quantize_moment_int8(leaf, k)
                    if to_int8
                    else stochastic_round_bf16(leaf, k)
                )
            else:
                out.append(leaf)
        return jax.tree.unflatten(treedef, out)

    def _decompress(tree):
        def _dq(leaf):
            if isinstance(leaf, Q8Moment):
                return dequantize_moment(leaf)
            if hasattr(leaf, "dtype") and leaf.dtype == jnp.bfloat16:
                return leaf.astype(jnp.float32)
            return leaf

        return jax.tree.map(
            _dq, tree, is_leaf=lambda x: isinstance(x, Q8Moment)
        )

    def init(params):
        key, sub = jax.random.split(jax.random.PRNGKey(seed))
        return LowBitMomentState(inner=_compress(tx.init(params), sub), key=key)

    def update(updates, state, params=None):
        new_updates, new_inner = tx.update(
            updates, _decompress(state.inner), params
        )
        key, sub = jax.random.split(state.key)
        return new_updates, LowBitMomentState(
            inner=_compress(new_inner, sub), key=key
        )

    return optax.GradientTransformation(init, update)


def _leaf_spec(
    leaf,
    axis_name: str,
    tp_axis: str | None = None,
    ep_axis: str | None = None,
    pp_axis: str | None = None,
):
    """The ZeRO layout rule, in one place: vector state (flat momentum,
    mu/nu chunks) is sharded along the data axis — jointly with any
    model axes (Megatron TP / expert EP) when params are sharded over
    them, since each model position flattens a DIFFERENT local param
    shard; scalars (step counts) stay replicated."""
    if getattr(leaf, "ndim", 0) < 1:
        return P()
    axes = (axis_name,) + tuple(
        a for a in (tp_axis, ep_axis, pp_axis) if a is not None
    )
    return P(axes if len(axes) > 1 else axis_name)


def opt_state_specs(
    tx: optax.GradientTransformation,
    chunk: int,
    axis_name: str = "data",
    tp_axis: str | None = None,
    ep_axis: str | None = None,
    pp_axis: str | None = None,
) -> Pytree:
    """PartitionSpec tree for a tx.init over a flat chunk."""
    shapes = jax.eval_shape(
        tx.init, jax.ShapeDtypeStruct((chunk,), jnp.float32)
    )
    return jax.tree.map(
        lambda s: _leaf_spec(s, axis_name, tp_axis, ep_axis, pp_axis), shapes
    )


def _param_specs(
    params: Pytree,
    tp_axis: str | None,
    ep_axis: str | None = None,
    pp_axis: str | None = None,
) -> Pytree:
    """Param layout for the ZeRO machinery: replicated, or the combined
    Megatron/expert layout when composing with TP/EP — and the stacked
    layer-dim pipeline layout (Megatron/expert rules composing
    underneath) when composing with PP.  The ONE spec source shared by
    init, state build, and the train step's in_specs."""
    if pp_axis is not None:
        from distributeddataparallel_tpu.parallel.pipeline_parallel import (
            pp_param_specs,
        )

        return pp_param_specs(params, pp_axis, tp_axis, ep_axis)
    from distributeddataparallel_tpu.parallel.expert_parallel import (
        model_axes_param_specs,
    )

    return model_axes_param_specs(params, tp_axis, ep_axis)


def _local_chunk(
    params: Pytree, param_specs: Pytree, mesh: Mesh, num_shards: int
) -> int:
    """Per-position flat chunk length when params are sharded by
    ``param_specs`` (host-side mirror of what ``flat_size`` sees on local
    shapes inside shard_map).  ``shard_shape`` raises on non-divisible
    dims, so a bad layout fails here, loudly, not as a downstream
    out_specs mismatch."""
    import math

    from jax.sharding import NamedSharding

    total = sum(
        math.prod(NamedSharding(mesh, spec).shard_shape(leaf.shape))
        for leaf, spec in zip(
            jax.tree.leaves(params), jax.tree.leaves(param_specs)
        )
    )
    return -(-total // num_shards)


def shard_opt_state(
    params: Pytree,
    tx: optax.GradientTransformation,
    mesh: Mesh,
    axis_name: str = "data",
    tp_axis: str | None = None,
    ep_axis: str | None = None,
    pp_axis: str | None = None,
    plan: BucketPlan | None = None,
) -> Pytree:
    """Initialize optimizer state sharded 1/N per mesh position.

    Each position runs ``tx.init`` on its own flat param chunk; vector
    state (momentum, mu/nu) therefore never exists fully replicated.
    Under ``tp_axis``/``ep_axis`` the flattened vector is each position's
    LOCAL Megatron/expert shard, so the flat state is additionally
    sharded over those model axes (state memory drops by the product of
    all the axis sizes per chip).

    With ``plan`` (zero2/zero3), the chunk uses the BUCKETED layout —
    the same ``BucketPlan`` the step's scatter/gather uses, so the opt
    vectors line up element-for-element with the scattered grads.
    """
    n = mesh.shape[axis_name]
    if plan is not None:

        def init_shard(p):
            return tx.init(shard_params_bucketed(p, plan, axis_name))

        pspecs = jax.tree.map(lambda _: P(), params)
        chunk = plan.local
    else:

        def init_shard(p):
            padded_l, chunk_l = flat_size(p, n)  # local (traced) shapes
            flat = flatten_f32(p, padded_l)
            idx = lax.axis_index(axis_name)
            return tx.init(
                lax.dynamic_slice(flat, (idx * chunk_l,), (chunk_l,))
            )

        pspecs = _param_specs(params, tp_axis, ep_axis, pp_axis)
        chunk = _local_chunk(params, pspecs, mesh, n)

    fn = jax.jit(
        jax.shard_map(
            init_shard,
            mesh=mesh,
            in_specs=(pspecs,),
            out_specs=opt_state_specs(
                tx, chunk, axis_name, tp_axis, ep_axis, pp_axis
            ),
            check_vma=False,
        )
    )
    return fn(params)


def zero_state(
    *,
    apply_fn,
    params: Pytree,
    tx: optax.GradientTransformation,
    mesh: Mesh,
    axis_name: str = "data",
    tp_axis: str | None = None,
    ep_axis: str | None = None,
    pp_axis: str | None = None,
    model_state: Pytree | None = None,
    level: int = 1,
    moment_dtype: str | None = None,
    bucket_bytes: int | None = None,
):
    """Build a TrainState whose optimizer state is ZeRO-sharded.

    Drop-in replacement for ``TrainState.create`` when using
    ``make_train_step(..., zero=level)``.  With ``tp_axis``/``ep_axis``,
    params are placed in the Megatron/expert layout and the flat
    optimizer state shards over ALL the axes — pass the same axes to
    ``make_train_step``.

    ``level``: 1 (sharded opt state, replicated params — the original
    path), 2 (bucketed layout, reduce-scattered grads), or 3 (params
    additionally stay sharded between steps as ``Zero3Params``).
    Levels 2/3 shard over the data axis only — compose model axes with
    level 1 or the fsdp path instead.  ``bucket_bytes`` sets the
    zero2/3 bucket granularity and MUST match the value given to
    ``make_train_step`` (both default to ``ZERO_BUCKET_BYTES``; a
    mismatch fails loudly as a flat-length mismatch at trace time).
    ``moment_dtype``: see ``low_bit_moments``.
    """
    from distributeddataparallel_tpu.training.state import TrainState

    level = int(level)
    if level not in (1, 2, 3):
        raise ValueError(f"zero_state: level={level!r} (want 1, 2, or 3)")
    if level >= 2 and (
        tp_axis is not None or ep_axis is not None or pp_axis is not None
    ):
        raise ValueError(
            "zero_state: level 2/3 shard over the data axis only; "
            "compose tp/ep/pp with level=1 or use the fsdp path"
        )
    tx = low_bit_moments(tx, moment_dtype)
    n = mesh.shape[axis_name]
    # The step counter rides the mesh replicated in EVERY layout: a
    # checkpoint restore uses the template's shardings leaf-for-leaf,
    # and an uncommitted scalar restores COMMITTED to device 0 — which
    # makes the restored state unsteppable next to mesh-committed
    # params/opt chunks.
    from jax.sharding import NamedSharding

    step0 = jax.device_put(
        jnp.zeros((), jnp.int32), NamedSharding(mesh, P())
    )

    if level == 3:
        plan = bucket_plan(params, n, bucket_bytes)
        meta = zero3_meta(params, n, plan)
        rep = jax.tree.map(lambda _: P(), params)

        def init_fn(p):
            flat = shard_params_bucketed(p, plan, axis_name)
            return flat, tx.init(flat)

        flat, opt_state = jax.jit(
            jax.shard_map(
                init_fn,
                mesh=mesh,
                in_specs=(rep,),
                out_specs=(
                    P(axis_name),
                    opt_state_specs(tx, plan.local, axis_name),
                ),
                check_vma=False,
            )
        )(params)
        return TrainState(
            step=step0,
            params=Zero3Params(flat=flat, meta=meta),
            opt_state=opt_state,
            model_state=model_state if model_state is not None else {},
            apply_fn=apply_fn,
            tx=tx,
        )

    if level == 2:
        plan = bucket_plan(params, n, bucket_bytes)
        return TrainState(
            step=step0,
            params=params,
            opt_state=shard_opt_state(params, tx, mesh, axis_name, plan=plan),
            model_state=model_state if model_state is not None else {},
            apply_fn=apply_fn,
            tx=tx,
        )

    if tp_axis is not None or ep_axis is not None or pp_axis is not None:
        params = jax.tree.map(
            lambda x, s: jax.device_put(x, NamedSharding(mesh, s)),
            params,
            _param_specs(params, tp_axis, ep_axis, pp_axis),
        )
    return TrainState(
        step=step0,
        params=params,
        opt_state=shard_opt_state(
            params, tx, mesh, axis_name, tp_axis, ep_axis, pp_axis
        ),
        model_state=model_state if model_state is not None else {},
        apply_fn=apply_fn,
        tx=tx,
    )


def zero_update(
    grads: Pytree,
    state,
    axis_name: str,
    num_shards: int,
    clip_norm: float | None = None,
    model_axes: tuple = (),
    local_specs: Pytree | None = None,
):
    """The sharded-update step body (runs inside shard_map).

    grads are this replica's *local* (unreduced) gradients; returns
    (new_params, new_opt_state) with params fully replicated again.
    ``num_shards`` is the static data-axis size (chunk sizes must be
    known at trace time).

    ``clip_norm``: clip the (synced) gradient to this global L2 norm —
    EXACT despite the sharded layout: the chunks partition the full
    gradient vector, so the global norm² is one psum of local chunk
    norm²s.  Under model-axis composition, pass ``model_axes`` (the
    tp/ep/pp mesh axis names) and ``local_specs`` (the per-leaf
    PartitionSpec tree for the local grads — the same tree the caller's
    in_specs came from): each position's flat holds its LOCAL tree, so
    model-sharded leaves appear once across positions while leaves
    replicated over an axis appear size(axis) times; elements are
    de-weighted by that duplicate count (``flat_chunk_sumsq``) before
    psumming over the data axis AND every model axis.
    """
    n = num_shards
    idx = lax.axis_index(axis_name)
    padded, chunk = flat_size(state.params, n)

    flat_g = flatten_f32(grads, padded)
    # reduce_scatter: each replica receives the SUM of its 1/N chunk,
    # divided for DDP mean semantics (ref dpp.py grad averaging).
    g_shard = lax.psum_scatter(
        flat_g, axis_name, scatter_dimension=0, tiled=True
    ) / n
    if clip_norm is not None:
        from distributeddataparallel_tpu.parallel.data_parallel import (
            clip_scale,
            flat_chunk_sumsq,
            spec_axes,
            sumsq_f32,
        )

        if model_axes:
            if local_specs is None:
                raise ValueError(
                    "clip under model_axes needs local_specs (the "
                    "per-leaf PartitionSpec tree of the local grads)"
                )
            # Per-leaf duplicate count: product of the model-axis sizes
            # the leaf is NOT sharded over (its copies across those
            # positions are identical).  Static at trace time.
            sizes = [l.size for l in jax.tree.leaves(grads)]
            dups = [
                int(np.prod([
                    lax.axis_size(ax)
                    for ax in model_axes
                    if ax not in spec_axes(sp)
                ] or [1]))
                for sp in jax.tree.leaves(
                    local_specs,
                    is_leaf=lambda x: isinstance(x, P),
                )
            ]
            s = flat_chunk_sumsq(g_shard, idx * chunk, sizes, dups)
            s = lax.psum(s, axis_name)
            for ax in model_axes:
                s = lax.psum(s, ax)
            gnorm = jnp.sqrt(s)
        else:
            gnorm = jnp.sqrt(lax.psum(sumsq_f32(g_shard), axis_name))
        g_shard = g_shard * clip_scale(gnorm, clip_norm)

    flat_p = flatten_f32(state.params, padded)
    p_shard = lax.dynamic_slice(flat_p, (idx * chunk,), (chunk,))

    updates, new_opt_state = state.tx.update(g_shard, state.opt_state, p_shard)
    new_p_shard = optax.apply_updates(p_shard, updates)

    new_flat = lax.all_gather(new_p_shard, axis_name, axis=0, tiled=True)
    new_params = unflatten(new_flat, state.params)
    return new_params, new_opt_state


def zero2_update(
    grads: Pytree,
    state,
    axis_name: str,
    num_shards: int,
    plan: BucketPlan,
    clip_norm: float | None = None,
):
    """ZeRO-2 step body (inside shard_map): per-bucket reduce-scatter of
    the local grads, sharded update, per-bucket all-gather of the new
    params.  ``plan`` must be the SAME plan the opt state was built with
    (``zero_state(level=2)``).  Clipping is exact: the bucketed shards
    partition the gradient vector (padding is zeros), so the global
    norm² is one psum of local chunk norm²s."""
    g_shard = scatter_grads_bucketed(grads, plan, axis_name, num_shards)
    if clip_norm is not None:
        from distributeddataparallel_tpu.parallel.data_parallel import (
            clip_scale,
            sumsq_f32,
        )

        gnorm = jnp.sqrt(lax.psum(sumsq_f32(g_shard), axis_name))
        g_shard = g_shard * clip_scale(gnorm, clip_norm)

    p_shard = shard_params_bucketed(state.params, plan, axis_name)
    updates, new_opt_state = state.tx.update(g_shard, state.opt_state, p_shard)
    new_p_shard = optax.apply_updates(p_shard, updates)
    new_params = gather_params_bucketed(
        new_p_shard, state.params, plan, axis_name
    )
    return new_params, new_opt_state


def zero3_update(
    g_shard,
    state,
    axis_name: str,
    num_shards: int,
    clip_norm: float | None = None,
):
    """ZeRO-3 step body (inside shard_map): the grads arrive ALREADY
    reduce-scattered — ``g_shard`` is the flat local cotangent of
    ``state.params.flat``, summed over the axis by the transpose of the
    bucketwise gather in forward.  Divide for mean semantics, update the
    shard, done: the new flat shard IS the next state's params (the
    re-gather happens at the top of the next step).  Returns
    (new_flat, new_opt_state)."""
    g_shard = g_shard / num_shards
    if clip_norm is not None:
        from distributeddataparallel_tpu.parallel.data_parallel import (
            clip_scale,
            sumsq_f32,
        )

        gnorm = jnp.sqrt(lax.psum(sumsq_f32(g_shard), axis_name))
        g_shard = g_shard * clip_scale(gnorm, clip_norm)

    p_shard = state.params.flat
    updates, new_opt_state = state.tx.update(g_shard, state.opt_state, p_shard)
    new_flat = optax.apply_updates(p_shard, updates)
    return new_flat, new_opt_state


def state_specs(
    state,
    axis_name: str = "data",
    tp_axis: str | None = None,
    ep_axis: str | None = None,
    pp_axis: str | None = None,
) -> Pytree:
    """Per-leaf PartitionSpec tree for a ZeRO TrainState: everything
    replicated except the flat (ndim>=1) optimizer-state vectors — and,
    under ``tp_axis``/``ep_axis``/``pp_axis``, the sharded params.  A
    zero3 state's ``Zero3Params.flat`` shards along the data axis."""
    opt_specs = jax.tree.map(
        lambda l: _leaf_spec(l, axis_name, tp_axis, ep_axis, pp_axis),
        state.opt_state,
    )
    if isinstance(state.params, Zero3Params):
        param_specs = Zero3Params(flat=P(axis_name), meta=state.params.meta)
    else:
        param_specs = _param_specs(state.params, tp_axis, ep_axis, pp_axis)
    return state.replace(
        step=P(),
        params=param_specs,
        opt_state=opt_specs,
        model_state=jax.tree.map(lambda _: P(), state.model_state),
    )
