"""ZeRO-1-style cross-replica weight-update sharding (TPU-native).

The reference trains pure-DP with fully replicated optimizer state
(`optim.SGD`, ref dpp.py:41) — every rank redundantly stores and updates
identical state.  For the Llama-3 8B config (BASELINE 5) that redundancy
is what breaks the per-chip memory budget (SURVEY.md §7 hard-part 3), and
the TPU-native fix is the cross-replica weight-update sharding of
arXiv 2004.13336 (the XLA-side ZeRO-1, referenced from PAPERS.md):

    grads --reduce_scatter--> 1/N grad shard per replica
          --optimizer update on the shard (opt state lives sharded)
          --all_gather--> full updated params on every replica

Same math as DDP+optimizer (identical updates, bitwise modulo reduction
order), ~same communication volume as one all-reduce (reduce_scatter +
all_gather = all_reduce's two phases), but optimizer state memory drops
N×: per chip, Adam on 8B goes from ~64 GB of f32 (mu+nu) to ~8 GB on an
8-way axis.

Mechanics: parameters/grads are flattened into one f32 vector padded to a
multiple of the axis size; each replica owns one contiguous chunk.  The
optimizer transform runs on that flat chunk — valid for elementwise
transforms (sgd, momentum, adam, adamw's decoupled decay).  Transforms
needing *global* tensor structure (clip_by_global_norm across the full
tree) would see only the local chunk; compose those upstream of the
train step or use replicated DP instead.

Used through ``training.train_step.make_train_step(..., zero=True)`` with
a state built by ``zero_state(...)``.
"""

from __future__ import annotations

from typing import Any

import jax
import jax.numpy as jnp
import numpy as np
import optax
from jax import lax
from jax.sharding import Mesh, PartitionSpec as P

Pytree = Any


def flat_size(params: Pytree, num_shards: int) -> tuple[int, int]:
    """(padded_total, chunk): total f32 elements padded to num_shards."""
    total = sum(leaf.size for leaf in jax.tree.leaves(params))
    chunk = -(-total // num_shards)
    return chunk * num_shards, chunk


def flatten_f32(params: Pytree, padded: int) -> jnp.ndarray:
    """Concat all leaves (cast f32) into one padded flat vector."""
    leaves = jax.tree.leaves(params)
    flat = jnp.concatenate([l.reshape(-1).astype(jnp.float32) for l in leaves])
    return jnp.pad(flat, (0, padded - flat.shape[0]))


def unflatten(flat: jnp.ndarray, like: Pytree) -> Pytree:
    """Inverse of flatten_f32: split `flat` back into `like`'s structure,
    casting each leaf to its original dtype."""
    leaves, treedef = jax.tree.flatten(like)
    out, offset = [], 0
    for leaf in leaves:
        n = leaf.size
        out.append(
            flat[offset : offset + n].reshape(leaf.shape).astype(leaf.dtype)
        )
        offset += n
    return jax.tree.unflatten(treedef, out)


def _leaf_spec(
    leaf,
    axis_name: str,
    tp_axis: str | None = None,
    ep_axis: str | None = None,
    pp_axis: str | None = None,
):
    """The ZeRO layout rule, in one place: vector state (flat momentum,
    mu/nu chunks) is sharded along the data axis — jointly with any
    model axes (Megatron TP / expert EP) when params are sharded over
    them, since each model position flattens a DIFFERENT local param
    shard; scalars (step counts) stay replicated."""
    if getattr(leaf, "ndim", 0) < 1:
        return P()
    axes = (axis_name,) + tuple(
        a for a in (tp_axis, ep_axis, pp_axis) if a is not None
    )
    return P(axes if len(axes) > 1 else axis_name)


def opt_state_specs(
    tx: optax.GradientTransformation,
    chunk: int,
    axis_name: str = "data",
    tp_axis: str | None = None,
    ep_axis: str | None = None,
    pp_axis: str | None = None,
) -> Pytree:
    """PartitionSpec tree for a tx.init over a flat chunk."""
    shapes = jax.eval_shape(
        tx.init, jax.ShapeDtypeStruct((chunk,), jnp.float32)
    )
    return jax.tree.map(
        lambda s: _leaf_spec(s, axis_name, tp_axis, ep_axis, pp_axis), shapes
    )


def _param_specs(
    params: Pytree,
    tp_axis: str | None,
    ep_axis: str | None = None,
    pp_axis: str | None = None,
) -> Pytree:
    """Param layout for the ZeRO machinery: replicated, or the combined
    Megatron/expert layout when composing with TP/EP — and the stacked
    layer-dim pipeline layout (Megatron/expert rules composing
    underneath) when composing with PP.  The ONE spec source shared by
    init, state build, and the train step's in_specs."""
    if pp_axis is not None:
        from distributeddataparallel_tpu.parallel.pipeline_parallel import (
            pp_param_specs,
        )

        return pp_param_specs(params, pp_axis, tp_axis, ep_axis)
    from distributeddataparallel_tpu.parallel.expert_parallel import (
        model_axes_param_specs,
    )

    return model_axes_param_specs(params, tp_axis, ep_axis)


def _local_chunk(
    params: Pytree, param_specs: Pytree, mesh: Mesh, num_shards: int
) -> int:
    """Per-position flat chunk length when params are sharded by
    ``param_specs`` (host-side mirror of what ``flat_size`` sees on local
    shapes inside shard_map).  ``shard_shape`` raises on non-divisible
    dims, so a bad layout fails here, loudly, not as a downstream
    out_specs mismatch."""
    import math

    from jax.sharding import NamedSharding

    total = sum(
        math.prod(NamedSharding(mesh, spec).shard_shape(leaf.shape))
        for leaf, spec in zip(
            jax.tree.leaves(params), jax.tree.leaves(param_specs)
        )
    )
    return -(-total // num_shards)


def shard_opt_state(
    params: Pytree,
    tx: optax.GradientTransformation,
    mesh: Mesh,
    axis_name: str = "data",
    tp_axis: str | None = None,
    ep_axis: str | None = None,
    pp_axis: str | None = None,
) -> Pytree:
    """Initialize optimizer state sharded 1/N per mesh position.

    Each position runs ``tx.init`` on its own flat param chunk; vector
    state (momentum, mu/nu) therefore never exists fully replicated.
    Under ``tp_axis``/``ep_axis`` the flattened vector is each position's
    LOCAL Megatron/expert shard, so the flat state is additionally
    sharded over those model axes (state memory drops by the product of
    all the axis sizes per chip).
    """
    n = mesh.shape[axis_name]
    pspecs = _param_specs(params, tp_axis, ep_axis, pp_axis)
    chunk = _local_chunk(params, pspecs, mesh, n)

    def init_shard(p):
        padded_l, chunk_l = flat_size(p, n)  # local (traced) shapes
        flat = flatten_f32(p, padded_l)
        idx = lax.axis_index(axis_name)
        return tx.init(lax.dynamic_slice(flat, (idx * chunk_l,), (chunk_l,)))

    fn = jax.jit(
        jax.shard_map(
            init_shard,
            mesh=mesh,
            in_specs=(pspecs,),
            out_specs=opt_state_specs(
                tx, chunk, axis_name, tp_axis, ep_axis, pp_axis
            ),
            check_vma=False,
        )
    )
    return fn(params)


def zero_state(
    *,
    apply_fn,
    params: Pytree,
    tx: optax.GradientTransformation,
    mesh: Mesh,
    axis_name: str = "data",
    tp_axis: str | None = None,
    ep_axis: str | None = None,
    pp_axis: str | None = None,
    model_state: Pytree | None = None,
):
    """Build a TrainState whose optimizer state is ZeRO-sharded.

    Drop-in replacement for ``TrainState.create`` when using
    ``make_train_step(..., zero=True)``.  With ``tp_axis``/``ep_axis``,
    params are placed in the Megatron/expert layout and the flat
    optimizer state shards over ALL the axes — pass the same axes to
    ``make_train_step``.
    """
    from distributeddataparallel_tpu.training.state import TrainState

    step = jnp.zeros((), jnp.int32)
    if tp_axis is not None or ep_axis is not None or pp_axis is not None:
        from jax.sharding import NamedSharding

        params = jax.tree.map(
            lambda x, s: jax.device_put(x, NamedSharding(mesh, s)),
            params,
            _param_specs(params, tp_axis, ep_axis, pp_axis),
        )
        # Scalars ride the mesh replicated too: a checkpoint restore uses
        # the template's shardings leaf-for-leaf, and a single-device
        # committed step counter next to mesh-committed params would make
        # the restored state unsteppable.
        step = jax.device_put(step, NamedSharding(mesh, P()))
    return TrainState(
        step=step,
        params=params,
        opt_state=shard_opt_state(
            params, tx, mesh, axis_name, tp_axis, ep_axis, pp_axis
        ),
        model_state=model_state if model_state is not None else {},
        apply_fn=apply_fn,
        tx=tx,
    )


def zero_update(
    grads: Pytree,
    state,
    axis_name: str,
    num_shards: int,
    clip_norm: float | None = None,
    model_axes: tuple = (),
    local_specs: Pytree | None = None,
):
    """The sharded-update step body (runs inside shard_map).

    grads are this replica's *local* (unreduced) gradients; returns
    (new_params, new_opt_state) with params fully replicated again.
    ``num_shards`` is the static data-axis size (chunk sizes must be
    known at trace time).

    ``clip_norm``: clip the (synced) gradient to this global L2 norm —
    EXACT despite the sharded layout: the chunks partition the full
    gradient vector, so the global norm² is one psum of local chunk
    norm²s.  Under model-axis composition, pass ``model_axes`` (the
    tp/ep/pp mesh axis names) and ``local_specs`` (the per-leaf
    PartitionSpec tree for the local grads — the same tree the caller's
    in_specs came from): each position's flat holds its LOCAL tree, so
    model-sharded leaves appear once across positions while leaves
    replicated over an axis appear size(axis) times; elements are
    de-weighted by that duplicate count (``flat_chunk_sumsq``) before
    psumming over the data axis AND every model axis.
    """
    n = num_shards
    idx = lax.axis_index(axis_name)
    padded, chunk = flat_size(state.params, n)

    flat_g = flatten_f32(grads, padded)
    # reduce_scatter: each replica receives the SUM of its 1/N chunk,
    # divided for DDP mean semantics (ref dpp.py grad averaging).
    g_shard = lax.psum_scatter(
        flat_g, axis_name, scatter_dimension=0, tiled=True
    ) / n
    if clip_norm is not None:
        from distributeddataparallel_tpu.parallel.data_parallel import (
            clip_scale,
            flat_chunk_sumsq,
            spec_axes,
            sumsq_f32,
        )

        if model_axes:
            if local_specs is None:
                raise ValueError(
                    "clip under model_axes needs local_specs (the "
                    "per-leaf PartitionSpec tree of the local grads)"
                )
            # Per-leaf duplicate count: product of the model-axis sizes
            # the leaf is NOT sharded over (its copies across those
            # positions are identical).  Static at trace time.
            sizes = [l.size for l in jax.tree.leaves(grads)]
            dups = [
                int(np.prod([
                    lax.axis_size(ax)
                    for ax in model_axes
                    if ax not in spec_axes(sp)
                ] or [1]))
                for sp in jax.tree.leaves(
                    local_specs,
                    is_leaf=lambda x: isinstance(x, P),
                )
            ]
            s = flat_chunk_sumsq(g_shard, idx * chunk, sizes, dups)
            s = lax.psum(s, axis_name)
            for ax in model_axes:
                s = lax.psum(s, ax)
            gnorm = jnp.sqrt(s)
        else:
            gnorm = jnp.sqrt(lax.psum(sumsq_f32(g_shard), axis_name))
        g_shard = g_shard * clip_scale(gnorm, clip_norm)

    flat_p = flatten_f32(state.params, padded)
    p_shard = lax.dynamic_slice(flat_p, (idx * chunk,), (chunk,))

    updates, new_opt_state = state.tx.update(g_shard, state.opt_state, p_shard)
    new_p_shard = optax.apply_updates(p_shard, updates)

    new_flat = lax.all_gather(new_p_shard, axis_name, axis=0, tiled=True)
    new_params = unflatten(new_flat, state.params)
    return new_params, new_opt_state


def state_specs(
    state,
    axis_name: str = "data",
    tp_axis: str | None = None,
    ep_axis: str | None = None,
    pp_axis: str | None = None,
) -> Pytree:
    """Per-leaf PartitionSpec tree for a ZeRO TrainState: everything
    replicated except the flat (ndim>=1) optimizer-state vectors — and,
    under ``tp_axis``/``ep_axis``/``pp_axis``, the sharded params."""
    opt_specs = jax.tree.map(
        lambda l: _leaf_spec(l, axis_name, tp_axis, ep_axis, pp_axis),
        state.opt_state,
    )
    return state.replace(
        step=P(),
        params=_param_specs(state.params, tp_axis, ep_axis, pp_axis),
        opt_state=opt_specs,
        model_state=jax.tree.map(lambda _: P(), state.model_state),
    )
