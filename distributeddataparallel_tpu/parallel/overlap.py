"""Demonstrated comm/compute overlap: DDP's defining perf property, TPU-native.

The reference's ``loss.backward()`` (ref dpp.py:52) hides the bucketed
NCCL all-reduce under the remaining backward computation — SURVEY.md §3.4
calls this "THE performance property to reproduce".  This module is where
the framework *demonstrates* the property rather than assuming XLA
provides it, because measured stock behavior is the opposite:

1. **Stock XLA serializes the gradient sync.**  The all-reduce combiner
   merges every per-leaf grad ``pmean`` into ONE tuple all-reduce whose
   inputs include the last-computed gradient, so it is scheduled after
   the *entire* backward — zero overlap by construction (verified on the
   TPU compiler: a single ``all-reduce`` at schedule position ~n-5 of n).

2. **The CPU test fabric cannot overlap at all.**  The XLA CPU backend
   emits only synchronous ``all-reduce`` (no ``-start``/``-done`` split,
   no async conversion), and on this machine the 8-device CPU mesh is
   time-sliced on ONE physical core (``len(os.sched_getaffinity(0)) ==
   1``) where inter-device "communication" is itself CPU work on that
   same core.  ``overlap_frac = 0.0`` on the CPU mesh is an architectural
   property of the fabric, not of this framework — hiding comm under
   compute cannot reduce wall time when both execute on the same core.

The TPU-native fix has two halves:

- ``bucket_gradients(..., chain=True)`` (parallel.data_parallel): DDP-style
  reverse-order buckets (1 MiB ``OVERLAP_BUCKET_BYTES`` default — large
  leaves ride solo in native dtype, which is what the async scheduler
  converts; 25 MiB concat buckets measure zero async windows), each
  barrier-chained to the previous bucket's output so the combiner cannot
  re-merge them.  Bucket k's all-reduce then depends only on the
  late-layer grads that backward produces *first*.

- ``OVERLAP_COMPILER_OPTIONS``: the TPU compiler's async-collective +
  latency-hiding-scheduler options.  With separate buckets available,
  the backend converts each bucket's all-reduce into an
  ``async-collective-start`` / ``async-collective-done`` pair (and fuses
  collectives *into* compute fusions — ``%async_collective_fusion.*``
  computations) and schedules real backward fusions inside the window.

``schedule_report`` extracts the proof from the compiled executable's own
scheduled HLO: per-window compute cycles (the compiler's
``estimated_cycles`` cost model) placed between each collective's start
and done.  ``grad_sync_schedule_evidence`` packages an end-to-end check
that AOT-compiles a DP train step for a multi-chip TPU topology (no
multi-chip hardware needed — ``jax.experimental.topologies``) and
reports the measured schedule.  Artifacts land in OVERLAP.md and the
bench/dryrun JSON sidecars.
"""

from __future__ import annotations

import re
from typing import Any

#: TPU compiler options that enable async collectives + the latency-hiding
#: scheduler.  Verified accepted by this image's TPU compiler; the CPU
#: compiler rejects TPU option names, hence the backend gate below.
OVERLAP_COMPILER_OPTIONS = {
    "xla_tpu_enable_latency_hiding_scheduler": "true",
    "xla_tpu_enable_async_collective_fusion": "true",
    "xla_tpu_enable_async_collective_fusion_fuse_all_reduce": "true",
    "xla_tpu_enable_async_collective_fusion_multiple_steps": "true",
    "xla_tpu_overlap_compute_collective_tc": "true",
    "xla_enable_async_all_reduce": "true",
}


class ScheduleEvidenceError(RuntimeError):
    """A live compile produced HLO the evidence parsers could not read.

    The schedule evidence is regex forensics over scheduled-HLO text; a
    compiler upgrade that renames ``async-collective-start`` or drops
    ``estimated_cycles`` must fail HERE, loudly, instead of recording a
    0-but-green artifact (VERDICT r4 weak 2)."""


def compiler_stamp() -> dict:
    """Version stamp for schedule-evidence artifacts: which compiler
    produced the HLO the parsers read.  Evidence without a stamp can't be
    audited across toolchain bumps."""
    import jax

    stamp = {"jax": jax.__version__}
    try:
        import jaxlib

        stamp["jaxlib"] = jaxlib.__version__
    except Exception:  # pragma: no cover - jaxlib always ships with jax
        pass
    try:
        stamp["backend_platform_version"] = jax.extend.backend.get_backend(
        ).platform_version
    except Exception:
        pass  # AOT-only processes may have no addressable backend
    return stamp


def validate_schedule_parse(rep: dict, hlo_text: str, *, where: str) -> dict:
    """Assert a live compile's schedule_report actually parsed something.

    Raises ``ScheduleEvidenceError`` when (a) the scheduled program shows
    zero ``estimated_cycles`` metadata (cost-model keys renamed/dropped)
    or (b) the HLO text contains collectives but the parser classified
    none (collective spellings drifted).  Returns ``rep`` so callers can
    chain.  Only for LIVE compiles — canned parser unit tests exercise
    ``schedule_report`` directly.
    """
    if rep["total_compute_cycles"] <= 0:
        raise ScheduleEvidenceError(
            f"{where}: scheduled HLO yielded zero parsed estimated_cycles "
            "— the compiler's cost-model metadata key has likely been "
            "renamed; the overlap evidence cannot be trusted"
        )
    has_collectives = re.search(
        r"\b(all-reduce|reduce-scatter|all-gather)", hlo_text
    )
    n_classified = (
        rep["n_async_windows"]
        + rep["n_sync_collectives"]
        + rep.get("n_comm_fused", 0)
    )
    if has_collectives and n_classified == 0:
        raise ScheduleEvidenceError(
            f"{where}: HLO contains collectives but the parser classified "
            "none — collective spellings have likely drifted; the overlap "
            "evidence cannot be trusted"
        )
    return rep


def overlap_compiler_options(backend: str | None = None) -> dict | None:
    """The OVERLAP_COMPILER_OPTIONS when targeting TPU, else None.

    Pass the result straight to ``jax.jit(..., compiler_options=...)``
    (None is accepted and means "no overrides").
    """
    import jax

    if backend is None:
        backend = jax.default_backend()
    return dict(OVERLAP_COMPILER_OPTIONS) if backend == "tpu" else None


def schedule_report(hlo_text: str) -> dict:
    """Quantify collective/compute overlap from scheduled HLO text.

    For TPU executables the ENTRY instruction order *is* the linear
    TensorCore schedule, and fusions carry the compiler's own
    ``estimated_cycles``.  The report pairs each
    ``async-collective-start``/``-done`` and sums the compute cycles
    scheduled inside the window — compute the TensorCore executes while
    the collective's DMAs are in flight.  Collective-carrying fusions
    (``async_collective_fusion`` computations: compute fused WITH a
    collective) count as overlapped compute too.

    Returns a dict with ``n_async_windows``, ``n_sync_collectives``
    (collectives left synchronous — the no-overlap failure mode),
    per-window cycle counts, and ``overlapped_frac_of_compute``.
    """
    # Computations that contain a collective op.
    ar_comps: set[str] = set()
    cur = None
    in_entry = False
    for line in hlo_text.splitlines():
        if line and not line.startswith(" ") and "{" in line:
            in_entry = line.lstrip().startswith("ENTRY")
            m = re.search(r"(%[\w.\-]+)\s*\(", line)
            if m:
                cur = m.group(1)
        if re.search(r"\ball-reduce\(|\breduce-scatter\(|\ball-gather\(", line):
            if cur and not in_entry:
                ar_comps.add(cur)

    entry = hlo_text[hlo_text.find("ENTRY"):]
    events: list[tuple[str, int]] = []  # (kind, cycles)
    for line in entry.splitlines():
        m = re.search(r"%([\w.\-]+) = ", line)
        if not m:
            continue
        name = m.group(1)
        cyc_m = re.search(r'"estimated_cycles":"(\d+)"', line)
        cycles = int(cyc_m.group(1)) if cyc_m else 0
        call_m = re.search(r"calls=(%[\w.\-]+)", line)
        callee = call_m.group(1) if call_m else None
        if name.startswith("async-collective-start") or re.search(
            r"\ball-reduce-start\(|\ball-gather-start\(", line
        ):
            events.append(("start", cycles))
        elif name.startswith("async-collective-done") or re.search(
            r"\ball-reduce-done\(|\ball-gather-done\(", line
        ):
            events.append(("done", cycles))
        elif callee in ar_comps or "async_collective_fusion" in (callee or ""):
            # Compute fused with a collective: overlapped by construction.
            events.append(("comm_fused", cycles))
        elif re.search(r"\ball-reduce\(|\breduce-scatter\(|\ball-gather\(", line):
            events.append(("sync_collective", cycles))
        elif re.search(r"= \S+ (fusion|custom-call|convolution)\(", line):
            events.append(("compute", cycles))

    windows: list[dict] = []
    depth = 0
    win_cycles = 0
    win_ops = 0
    total_compute = 0
    n_sync = 0
    n_comm_fused = sum(1 for kind, _ in events if kind == "comm_fused")
    for kind, cycles in events:
        if kind == "start":
            depth += 1
            if depth == 1:
                win_cycles, win_ops = 0, 0
        elif kind == "done":
            if depth > 0:
                depth -= 1
                if depth == 0:
                    windows.append(
                        {"compute_cycles": win_cycles, "n_compute_ops": win_ops}
                    )
        elif kind == "sync_collective":
            n_sync += 1
        else:  # compute / comm_fused
            total_compute += cycles
            if depth > 0 and cycles:
                win_cycles += cycles
                win_ops += 1

    overlapped = sum(w["compute_cycles"] for w in windows)
    return {
        "n_async_windows": len(windows),
        "n_sync_collectives": n_sync,
        "n_comm_fused": n_comm_fused,
        "windows": windows,
        "total_compute_cycles": total_compute,
        "overlapped_compute_cycles": overlapped,
        "overlapped_frac_of_compute": (
            round(overlapped / total_compute, 4) if total_compute else 0.0
        ),
    }


def cycles_by_scope(
    hlo_text: str, buckets: dict[str, str], *, strict: bool = False
) -> dict:
    """Bucket the scheduled program's ``estimated_cycles`` by op scope.

    ``buckets`` maps bucket name -> regex matched against each
    instruction's ``op_name`` metadata (the jax trace scope, e.g.
    ``.../Attention_0/q_proj/...``); first match wins, unmatched cycles
    land in ``other``.  Walks EVERY computation (fusion cycles live on
    the call sites in entry AND inside while/cond bodies), skipping
    fusion-body internals by only counting lines that carry
    ``estimated_cycles``.  A measured decomposition of where the
    compiler thinks the time goes — the MFU-gap attribution tool.
    """
    compiled = {k: re.compile(v, re.IGNORECASE) for k, v in buckets.items()}
    out = {k: 0 for k in buckets}
    out["other"] = 0
    seen_calls: set[str] = set()
    for line in hlo_text.splitlines():
        cyc = re.search(r'"estimated_cycles":"(\d+)"', line)
        if not cyc:
            continue
        callm = re.search(r"calls=(%[\w.\-]+)", line)
        if callm:
            # one count per called computation (call sites repeat in
            # schedules that unroll)
            if callm.group(1) in seen_calls:
                continue
            seen_calls.add(callm.group(1))
        name_m = re.search(r'op_name="([^"]*)"', line)
        scope = name_m.group(1) if name_m else ""
        n = int(cyc.group(1))
        for k, rx in compiled.items():
            if rx.search(scope):
                out[k] += n
                break
        else:
            out["other"] += n
    total = sum(out.values())
    if strict and total == 0:
        raise ScheduleEvidenceError(
            "cycles_by_scope: zero estimated_cycles parsed from a live "
            "compile — cost-model metadata key renamed?"
        )
    return {
        "total_cycles": total,
        "by_scope": out,
        "frac": {
            k: round(v / total, 4) if total else 0.0
            for k, v in out.items()
        },
    }


def tpu_topology_mesh(topology: str = "v5e:2x4", axis_names=("data",),
                      shape=None):
    """An n-chip TPU Mesh from an AOT topology description — no multi-chip
    hardware required (``jax.experimental.topologies``).  Programs built
    on this mesh can be ``.lower().compile()``d (not run) to inspect what
    the real TPU compiler does at scale."""
    import numpy as np
    from jax.experimental import topologies
    from jax.sharding import Mesh

    topo = topologies.get_topology_desc(platform="tpu", topology_name=topology)
    devs = np.array(topo.devices)
    if shape is None:
        shape = (devs.size,) if len(axis_names) == 1 else None
    return Mesh(devs.reshape(shape), axis_names)


def grad_sync_schedule_evidence(
    *,
    topology: str = "v5e:2x4",
    n_layers: int = 8,
    d_model: int = 2048,
    batch_per_chip: int = 32,
    bucket_bytes: int | None = None,
    chain: bool = True,
    return_hlo: bool = False,
) -> dict:
    """AOT-compile a DP grad-sync step for a multi-chip TPU topology and
    report the scheduled overlap (``schedule_report``).

    The program is the DDP kernel in miniature: an ``n_layers`` MLP
    forward+backward with per-bucket chained pmean of the gradients —
    one bucket per layer by default (``bucket_bytes=None`` → leaf-sized
    buckets), matching the granularity DDP's Reducer sees.  With
    ``chain=False`` the same program shows the stock-XLA failure mode
    (combiner merges to one post-backward all-reduce) for comparison.
    """
    import jax
    import jax.numpy as jnp
    from jax import lax
    from jax.sharding import PartitionSpec as P

    from distributeddataparallel_tpu.parallel.data_parallel import (
        bucket_gradients,
    )

    mesh = tpu_topology_mesh(topology)
    n_chips = mesh.devices.size

    def step(w, x):
        def loss(w, x):
            h = x
            for wi in w:
                h = jnp.tanh(h @ wi)
            return jnp.sum(h.astype(jnp.float32) ** 2)

        g = jax.grad(loss)(w, x)
        if chain:
            bb = bucket_bytes or (d_model * d_model * 2)  # one leaf/bucket
            g = bucket_gradients(g, "data", bucket_bytes=bb, chain=True)
        else:
            g = jax.tree.map(lambda t: lax.pmean(t, "data"), g)
        return g

    fn = jax.jit(
        jax.shard_map(
            step, mesh=mesh, in_specs=(P(), P("data")), out_specs=P(),
            check_vma=False,
        )
    )
    w = [
        jax.ShapeDtypeStruct((d_model, d_model), jnp.bfloat16)
        for _ in range(n_layers)
    ]
    x = jax.ShapeDtypeStruct((batch_per_chip * n_chips, d_model), jnp.bfloat16)
    txt = (
        fn.lower(w, x)
        .compile(compiler_options=dict(OVERLAP_COMPILER_OPTIONS))
        .as_text()
    )
    rep = validate_schedule_parse(
        schedule_report(txt), txt, where="grad_sync_schedule_evidence"
    )
    rep.update(
        {
            "topology": topology,
            "n_chips": n_chips,
            "compiler": compiler_stamp(),
            "config": {
                "n_layers": n_layers,
                "d_model": d_model,
                "batch_per_chip": batch_per_chip,
                "chain": chain,
                "bucket_bytes": bucket_bytes,
            },
        }
    )
    if return_hlo:
        rep["hlo_text"] = txt
    return rep


def grad_sync_schedule_pair(**kwargs) -> dict:
    """The chain-vs-stock evidence pair, packaged for artifacts.

    One definition shared by the dryrun (MULTICHIP_PROBES.json) and the
    bench (BENCH_r{N}.json) so the two recorded protocols cannot drift.
    Raises if no TPU compiler is reachable — callers decide how to
    degrade.
    """
    sched = grad_sync_schedule_evidence(chain=True, **kwargs)
    stock = grad_sync_schedule_evidence(chain=False, **kwargs)
    keys = (
        "n_async_windows", "n_sync_collectives",
        "overlapped_compute_cycles", "total_compute_cycles",
        "overlapped_frac_of_compute", "topology", "n_chips", "compiler",
    )
    return {
        "tpu_schedule": {k: sched[k] for k in keys},
        "tpu_schedule_stock_xla": {
            k: stock[k]
            for k in ("n_async_windows", "overlapped_frac_of_compute")
        },
    }


def cpu_fabric_note() -> dict:
    """Machine-checked statement of why overlap cannot appear on the CPU
    test mesh: single-core fabric + synchronous-only CPU collectives.
    Returned as data so dryrun/bench artifacts carry the evidence."""
    import os

    import jax

    note = {
        "physical_cores": len(os.sched_getaffinity(0)),
        "claim": (
            "XLA:CPU lowers collectives as synchronous all-reduce (no "
            "start/done split, no async conversion pass), and the virtual "
            "8-device mesh time-slices one physical core where "
            "inter-device reduction is itself CPU work on that core — "
            "step_time >= compute + comm by construction, so "
            "overlap_frac=0.0 measures the fabric, not the framework. "
            "See parallel/overlap.py and OVERLAP.md for the TPU-schedule "
            "demonstration of the property."
        ),
    }
    # Verify the sync-only claim against the live compiler when this
    # process is on the CPU backend (cheap: tiny program).
    try:
        if jax.default_backend() == "cpu" and len(jax.devices()) > 1:
            import numpy as np
            import jax.numpy as jnp
            from jax import lax
            from jax.sharding import Mesh, PartitionSpec as P

            n = len(jax.devices())
            m = Mesh(np.array(jax.devices()), ("d",))
            f = jax.jit(
                jax.shard_map(
                    lambda t: lax.psum(t, "d"), mesh=m, in_specs=P(),
                    out_specs=P(), check_vma=False,
                )
            )
            txt = f.lower(jnp.ones((128,), jnp.float32)).compile().as_text()
            note["cpu_hlo_sync_allreduce"] = " all-reduce(" in txt
            note["cpu_hlo_async_allreduce"] = "all-reduce-start" in txt
    except Exception as exc:  # pragma: no cover - evidence gathering only
        note["verify_error"] = repr(exc)
    return note
