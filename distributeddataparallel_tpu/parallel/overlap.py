"""Demonstrated comm/compute overlap: DDP's defining perf property, TPU-native.

The reference's ``loss.backward()`` (ref dpp.py:52) hides the bucketed
NCCL all-reduce under the remaining backward computation — SURVEY.md §3.4
calls this "THE performance property to reproduce".  This module is where
the framework *demonstrates* the property rather than assuming XLA
provides it, because measured stock behavior is the opposite:

1. **Stock XLA serializes the gradient sync.**  The all-reduce combiner
   merges every per-leaf grad ``pmean`` into ONE tuple all-reduce whose
   inputs include the last-computed gradient, so it is scheduled after
   the *entire* backward — zero overlap by construction (verified on the
   TPU compiler: a single ``all-reduce`` at schedule position ~n-5 of n).

2. **The CPU test fabric cannot overlap at all.**  The XLA CPU backend
   emits only synchronous ``all-reduce`` (no ``-start``/``-done`` split,
   no async conversion), and on this machine the 8-device CPU mesh is
   time-sliced on ONE physical core (``len(os.sched_getaffinity(0)) ==
   1``) where inter-device "communication" is itself CPU work on that
   same core.  ``overlap_frac = 0.0`` on the CPU mesh is an architectural
   property of the fabric, not of this framework — hiding comm under
   compute cannot reduce wall time when both execute on the same core.

The TPU-native fix has two halves:

- ``bucket_gradients(..., chain=True)`` (parallel.data_parallel): DDP-style
  reverse-order buckets (1 MiB ``OVERLAP_BUCKET_BYTES`` default — large
  leaves ride solo in native dtype, which is what the async scheduler
  converts; 25 MiB concat buckets measure zero async windows), each
  barrier-chained to the previous bucket's output so the combiner cannot
  re-merge them.  Bucket k's all-reduce then depends only on the
  late-layer grads that backward produces *first*.

- ``OVERLAP_COMPILER_OPTIONS``: the TPU compiler's async-collective +
  latency-hiding-scheduler options.  With separate buckets available,
  the backend converts each bucket's all-reduce into an
  ``async-collective-start`` / ``async-collective-done`` pair (and fuses
  collectives *into* compute fusions — ``%async_collective_fusion.*``
  computations) and schedules real backward fusions inside the window.

``schedule_report`` extracts the proof from the compiled executable's own
scheduled HLO: per-window compute cycles (the compiler's
``estimated_cycles`` cost model) placed between each collective's start
and done.  ``grad_sync_schedule_evidence`` packages an end-to-end check
that AOT-compiles a DP train step for a multi-chip TPU topology (no
multi-chip hardware needed — ``jax.experimental.topologies``) and
reports the measured schedule.  Artifacts land in OVERLAP.md and the
bench/dryrun JSON sidecars.
"""

from __future__ import annotations

import re
from typing import Any

#: TPU compiler options that enable async collectives + the latency-hiding
#: scheduler.  Verified accepted by this image's TPU compiler; the CPU
#: compiler rejects TPU option names, hence the backend gate below.
OVERLAP_COMPILER_OPTIONS = {
    "xla_tpu_enable_latency_hiding_scheduler": "true",
    "xla_tpu_enable_async_collective_fusion": "true",
    "xla_tpu_enable_async_collective_fusion_fuse_all_reduce": "true",
    "xla_tpu_enable_async_collective_fusion_multiple_steps": "true",
    "xla_tpu_overlap_compute_collective_tc": "true",
    "xla_enable_async_all_reduce": "true",
    # Disable the cross-replica-sum combiner so per-bucket all-reduces
    # stay separate WITHOUT data-dependence barriers.  Measured on the
    # real GPT-2 124M step (v5e:2x4 AOT): barrier-chained buckets reach
    # 12.3% scheduled overlap (the chain serializes the collectives and
    # triples compile time); unchained buckets with the combiner off
    # reach 19.1% with every weight-sized all-reduce async — only
    # sub-MiB concat buckets (~0.3 MB of 498 MB) stay synchronous.
    "xla_jf_crs_combiner_threshold_in_bytes": "1",
}


class ScheduleEvidenceError(RuntimeError):
    """A live compile produced HLO the evidence parsers could not read.

    The schedule evidence is regex forensics over scheduled-HLO text; a
    compiler upgrade that renames ``async-collective-start`` or drops
    ``estimated_cycles`` must fail HERE, loudly, instead of recording a
    0-but-green artifact (VERDICT r4 weak 2)."""


def compiler_stamp() -> dict:
    """Version stamp for schedule-evidence artifacts: which compiler
    produced the HLO the parsers read.  Evidence without a stamp can't be
    audited across toolchain bumps."""
    import jax

    stamp = {"jax": jax.__version__}
    try:
        import jaxlib

        stamp["jaxlib"] = jaxlib.__version__
    except ImportError:  # pragma: no cover - jaxlib always ships with jax
        pass
    try:
        stamp["backend_platform_version"] = jax.extend.backend.get_backend(
        ).platform_version
    except (RuntimeError, AttributeError):
        pass  # AOT-only processes may have no addressable backend
    return stamp


def validate_schedule_parse(rep: dict, hlo_text: str, *, where: str) -> dict:
    """Assert a live compile's schedule_report actually parsed something.

    Raises ``ScheduleEvidenceError`` when (a) the scheduled program shows
    zero ``estimated_cycles`` metadata (cost-model keys renamed/dropped)
    or (b) the HLO text contains collectives but the parser classified
    none (collective spellings drifted).  Returns ``rep`` so callers can
    chain.  Only for LIVE compiles — canned parser unit tests exercise
    ``schedule_report`` directly.
    """
    if rep["total_compute_cycles"] <= 0:
        raise ScheduleEvidenceError(
            f"{where}: scheduled HLO yielded zero parsed estimated_cycles "
            "— the compiler's cost-model metadata key has likely been "
            "renamed; the overlap evidence cannot be trusted"
        )
    has_collectives = re.search(
        r"\b(all-reduce|reduce-scatter|all-gather)", hlo_text
    )
    n_classified = (
        rep["n_async_windows"]
        + rep["n_sync_collectives"]
        + rep.get("n_comm_fused", 0)
    )
    if has_collectives and n_classified == 0:
        raise ScheduleEvidenceError(
            f"{where}: HLO contains collectives but the parser classified "
            "none — collective spellings have likely drifted; the overlap "
            "evidence cannot be trusted"
        )
    return rep


def overlap_compiler_options(backend: str | None = None) -> dict | None:
    """The OVERLAP_COMPILER_OPTIONS when targeting TPU, else None.

    Pass the result straight to ``jax.jit(..., compiler_options=...)``
    (None is accepted and means "no overrides").
    """
    import jax

    if backend is None:
        backend = jax.default_backend()
    return dict(OVERLAP_COMPILER_OPTIONS) if backend == "tpu" else None


def _split_computations(hlo_text: str) -> dict[str, list[str]]:
    """HLO text → {computation_name: body lines}.  Computations start at
    column 0 with ``[ENTRY ]%name (params) -> ... {`` and end at a
    column-0 ``}``; the ENTRY computation is keyed ``"ENTRY"``."""
    comps: dict[str, list[str]] = {}
    cur: list[str] | None = None
    for line in hlo_text.splitlines():
        if line and not line.startswith(" ") and "{" in line:
            is_entry = line.lstrip().startswith("ENTRY")
            m = re.search(r"(%[\w.\-]+)\s*\(", line)
            if m:
                cur = comps.setdefault("ENTRY" if is_entry else m.group(1), [])
            continue
        if line.startswith("}"):
            cur = None
            continue
        if cur is not None:
            cur.append(line)
    return comps


_DTYPE_BYTES = {
    "pred": 1, "s8": 1, "u8": 1, "f8e4m3fn": 1, "f8e5m2": 1,
    "s16": 2, "u16": 2, "f16": 2, "bf16": 2,
    "s32": 4, "u32": 4, "f32": 4,
    "s64": 8, "u64": 8, "f64": 8, "c64": 8, "c128": 16,
}


def _shape_bytes(line: str) -> int:
    """Payload bytes of an instruction's (first) result shape — for
    collective-done / sync-collective lines, whose single output IS the
    reduced payload (tuple-typed lines take the first element)."""
    m = re.search(r"= \(?(\w+)\[([\d,]*)\]", line)
    if not m:
        return 0
    dt, dims = m.groups()
    n = 1
    for d in dims.split(","):
        if d:
            n *= int(d)
    return n * _DTYPE_BYTES.get(dt, 4)


def _parse_events(
    lines: list[str],
    ar_comps: set[str],
    ar_payload: dict[str, int] | None = None,
):
    """One computation's scheduled lines → [(kind, cycles, bytes)].

    ``ar_payload`` maps collective-carrying computation name → the sum
    of its collectives' RESULT bytes — the payload attribution for
    collective-carrying fusions, whose own result tuple leads with the
    fused COMPUTE outputs (using the call-site shape would credit those
    compute bytes to the collective).
    """
    ar_payload = ar_payload or {}
    events: list[tuple[str, int, int, str]] = []
    for line in lines:
        m = re.search(r"%([\w.\-]+) = ", line)
        if not m:
            continue
        name = m.group(1)
        cyc_m = re.search(r'"estimated_cycles":"(\d+)"', line)
        cycles = int(cyc_m.group(1)) if cyc_m else 0
        call_m = re.search(r"calls=(%[\w.\-]+)", line)
        callee = call_m.group(1) if call_m else None
        if name.startswith("async-collective-start") or re.search(
            r"\ball-reduce-start\(|\ball-gather-start\(", line
        ):
            events.append(("start", cycles, 0, name))
        elif name.startswith("async-collective-done") or re.search(
            r"\ball-reduce-done\(|\ball-gather-done\(", line
        ):
            # done's single result is the reduced payload: bytes land here
            events.append(("done", cycles, _shape_bytes(line), name))
        elif callee in ar_comps or "async_collective_fusion" in (callee or ""):
            # Compute fused with a collective: overlapped by construction.
            events.append(
                ("comm_fused", cycles, ar_payload.get(callee, 0), name)
            )
        elif re.search(r"\ball-reduce\(|\breduce-scatter\(|\ball-gather\(", line):
            events.append(("sync_collective", cycles, _shape_bytes(line), name))
        elif re.search(r" (fusion|custom-call|convolution)\(", line):
            # note: matches tuple-typed (multi-output) fusions too, which
            # the pre-round-5 `= \S+ fusion(` spelling silently missed
            events.append(("compute", cycles, 0, name))
    return events


def _tally(events) -> dict:
    """Fold an event stream into windows/compute/sync counts and
    async-vs-sync collective payload bytes."""
    windows: list[dict] = []
    depth = 0
    win_cycles = 0
    win_ops = 0
    total_compute = 0
    n_sync = 0
    async_bytes = 0
    sync_bytes = 0
    sync_detail: list[dict] = []
    n_comm_fused = sum(1 for kind, _, _, _ in events if kind == "comm_fused")
    for kind, cycles, nbytes, name in events:
        if kind == "start":
            depth += 1
            if depth == 1:
                win_cycles, win_ops = 0, 0
        elif kind == "done":
            async_bytes += nbytes
            if depth > 0:
                depth -= 1
                if depth == 0:
                    windows.append(
                        {"compute_cycles": win_cycles, "n_compute_ops": win_ops}
                    )
        elif kind == "sync_collective":
            n_sync += 1
            sync_bytes += nbytes
            sync_detail.append({"bytes": nbytes, "name": name})
        else:  # compute / comm_fused
            total_compute += cycles
            if kind == "comm_fused":
                async_bytes += nbytes
            if depth > 0 and cycles:
                win_cycles += cycles
                win_ops += 1
    sync_detail.sort(key=lambda d: -d["bytes"])
    return {
        "windows": windows,
        "total_compute": total_compute,
        "n_sync": n_sync,
        "n_comm_fused": n_comm_fused,
        "async_bytes": async_bytes,
        "sync_bytes": sync_bytes,
        "sync_detail": sync_detail,
    }


def schedule_report(
    hlo_text: str, *, while_trip_counts: dict[str, int] | None = None
) -> dict:
    """Quantify collective/compute overlap from scheduled HLO text.

    For TPU executables the ENTRY instruction order *is* the linear
    TensorCore schedule, and fusions carry the compiler's own
    ``estimated_cycles``.  The report pairs each
    ``async-collective-start``/``-done`` and sums the compute cycles
    scheduled inside the window — compute the TensorCore executes while
    the collective's DMAs are in flight.  Collective-carrying fusions
    (``async_collective_fusion`` computations: compute fused WITH a
    collective) count as overlapped compute too.

    **While loops** (``lax.scan``-lowered layer stacks): the bodies of
    while ops reachable from ENTRY are tallied with the same event
    logic and folded into the totals — without this, a scanned model's
    backward (which lives almost entirely inside the loop) would vanish
    from the denominator and inflate the overlap fraction.  Each body
    counts ``while_trip_counts[regex-matched body name]`` times (the
    caller knows the static layer count; unmatched bodies default to 1,
    the conservative floor for the numerator AND denominator — the
    report then carries the body under ``while_bodies`` so the
    under-count is visible, never silent).

    Returns ``n_async_windows``, ``n_sync_collectives`` (collectives
    left synchronous — the no-overlap failure mode), per-window cycle
    counts, per-body sub-reports, and ``overlapped_frac_of_compute``.
    """
    comps = _split_computations(hlo_text)

    # Computations that contain a collective op (async wrapper targets),
    # with the payload bytes of the collectives they carry.
    ar_comps: set[str] = set()
    ar_payload: dict[str, int] = {}
    for name, lines in comps.items():
        if name == "ENTRY":
            continue
        hits = [
            l
            for l in lines
            if re.search(
                r"\ball-reduce\(|\breduce-scatter\(|\ball-gather\(", l
            )
        ]
        if hits:  # collective-carrying even when no shape parses (0 B)
            ar_comps.add(name)
            ar_payload[name] = sum(_shape_bytes(l) for l in hits)

    entry_lines = comps.get("ENTRY", [])
    tally = _tally(_parse_events(entry_lines, ar_comps, ar_payload))

    # While bodies reachable from ENTRY (scan-lowered layer loops).
    body_names: list[str] = []
    for line in entry_lines:
        if re.search(r"\bwhile\(", line):
            m = re.search(r"body=(%[\w.\-]+)", line)
            if m:
                body_names.append(m.group(1))

    windows = list(tally["windows"])
    total_compute = tally["total_compute"]
    overlapped = sum(w["compute_cycles"] for w in windows)
    n_windows = len(windows)
    n_sync = tally["n_sync"]
    n_comm_fused = tally["n_comm_fused"]
    async_bytes = tally["async_bytes"]
    sync_bytes = tally["sync_bytes"]
    while_bodies: list[dict] = []
    for bname in body_names:
        blines = comps.get(bname)
        if not blines:
            continue
        btally = _tally(_parse_events(blines, ar_comps, ar_payload))
        trips = 1
        if while_trip_counts:
            for pat, n in while_trip_counts.items():
                if re.search(pat, bname):
                    trips = n
                    break
        b_overlapped = sum(w["compute_cycles"] for w in btally["windows"])
        while_bodies.append(
            {
                "body": bname,
                "trip_count": trips,
                "compute_cycles_per_trip": btally["total_compute"],
                "n_async_windows_per_trip": len(btally["windows"]),
                "n_sync_collectives_per_trip": btally["n_sync"],
                "overlapped_compute_cycles_per_trip": b_overlapped,
            }
        )
        total_compute += btally["total_compute"] * trips
        overlapped += b_overlapped * trips
        n_windows += len(btally["windows"]) * trips
        n_sync += btally["n_sync"] * trips
        n_comm_fused += btally["n_comm_fused"] * trips
        async_bytes += btally["async_bytes"] * trips
        sync_bytes += btally["sync_bytes"] * trips

    coll_bytes = async_bytes + sync_bytes
    return {
        "n_async_windows": n_windows,
        "n_sync_collectives": n_sync,
        "n_comm_fused": n_comm_fused,
        "windows": windows,
        "while_bodies": while_bodies,
        "total_compute_cycles": total_compute,
        "overlapped_compute_cycles": overlapped,
        "overlapped_frac_of_compute": (
            round(overlapped / total_compute, 4) if total_compute else 0.0
        ),
        # payload bytes moved by async (start/done or collective-fused)
        # vs synchronous collectives: the DDP-parity claim is that the
        # weight-sized gradient traffic rides async.
        "async_collective_bytes": async_bytes,
        "sync_collective_bytes": sync_bytes,
        "async_bytes_frac": (
            round(async_bytes / coll_bytes, 4) if coll_bytes else 0.0
        ),
        # the sync residue itself, largest first (ENTRY-level only):
        # what stayed synchronous and how big — the tuning target.
        "sync_collective_detail": tally["sync_detail"][:16],
    }


def cycles_by_scope(
    hlo_text: str, buckets: dict[str, str], *, strict: bool = False
) -> dict:
    """Bucket the scheduled program's ``estimated_cycles`` by op scope.

    ``buckets`` maps bucket name -> regex matched against each
    instruction's ``op_name`` metadata (the jax trace scope, e.g.
    ``.../Attention_0/q_proj/...``); first match wins, unmatched cycles
    land in ``other``.  Walks EVERY computation (fusion cycles live on
    the call sites in entry AND inside while/cond bodies), skipping
    fusion-body internals by only counting lines that carry
    ``estimated_cycles``.  A measured decomposition of where the
    compiler thinks the time goes — the per-op half of MFU-gap
    attribution (``observability.cost_model`` supplies the other half:
    the analytic FLOP numerator the gap is measured against).
    """
    compiled = {k: re.compile(v, re.IGNORECASE) for k, v in buckets.items()}
    out = {k: 0 for k in buckets}
    out["other"] = 0
    seen_calls: set[str] = set()
    for line in hlo_text.splitlines():
        cyc = re.search(r'"estimated_cycles":"(\d+)"', line)
        if not cyc:
            continue
        callm = re.search(r"calls=(%[\w.\-]+)", line)
        if callm:
            # one count per called computation (call sites repeat in
            # schedules that unroll)
            if callm.group(1) in seen_calls:
                continue
            seen_calls.add(callm.group(1))
        name_m = re.search(r'op_name="([^"]*)"', line)
        scope = name_m.group(1) if name_m else ""
        n = int(cyc.group(1))
        for k, rx in compiled.items():
            if rx.search(scope):
                out[k] += n
                break
        else:
            out["other"] += n
    total = sum(out.values())
    if strict and total == 0:
        raise ScheduleEvidenceError(
            "cycles_by_scope: zero estimated_cycles parsed from a live "
            "compile — cost-model metadata key renamed?"
        )
    return {
        "total_cycles": total,
        "by_scope": out,
        "frac": {
            k: round(v / total, 4) if total else 0.0
            for k, v in out.items()
        },
    }


_TPU_TOPOLOGY_PROBE: dict[str, bool] = {}


def _probe_tpu_topology(topology: str, timeout_s: float = 20.0) -> None:
    """Raise unless TPU AOT topology init is known to complete.

    On a host with the TPU PJRT plugin installed but no TPU runtime,
    ``get_topology_desc`` can block forever inside the plugin's C++
    initialization (a retry loop the Python caller cannot interrupt)
    instead of raising.  Probing in a throwaway subprocess under a
    deadline converts that wedge into the prompt ``RuntimeError`` every
    caller's degrade path already handles.  The verdict is cached per
    topology string, so a process pays for the probe at most once.
    """
    if topology not in _TPU_TOPOLOGY_PROBE:
        import os
        import subprocess
        import sys

        # Scrub the child env: a supervised gang worker carries
        # distributed-init vars (JAX_COORDINATOR_ADDRESS & co) and chaos
        # wiring that the probe must not inherit — the throwaway child
        # would block rendezvousing with a gang it isn't part of, and
        # the 20s deadline would misread "waiting on a coordinator" as
        # "plugin wedged".
        child_env = {
            k: v for k, v in os.environ.items()
            if k not in (
                "JAX_COORDINATOR_ADDRESS", "JAX_NUM_PROCESSES",
                "JAX_PROCESS_ID", "CLOUD_TPU_TASK_ID", "TPU_WORKER_ID",
            ) and not k.startswith("DDP_")
        }
        # Exit sentinel 3 = "plugin raised cleanly" (no TPU runtime /
        # no plugin): an expected skip, unlike a crash or a wedge.
        code = (
            "import sys\n"
            "try:\n"
            "    from jax.experimental.topologies import "
            "get_topology_desc\n"
            f"    get_topology_desc(platform='tpu', "
            f"topology_name={topology!r})\n"
            "except Exception:\n"
            "    sys.exit(3)\n"
        )
        try:
            res = subprocess.run(
                [sys.executable, "-c", code],
                capture_output=True,
                timeout=timeout_s,
                env=child_env,
            )
            _TPU_TOPOLOGY_PROBE[topology] = res.returncode == 0
        except subprocess.TimeoutExpired:
            _TPU_TOPOLOGY_PROBE[topology] = False
    if not _TPU_TOPOLOGY_PROBE[topology]:
        raise RuntimeError(
            f"TPU AOT topology {topology!r} unavailable: plugin init "
            f"failed or wedged past {timeout_s:.0f}s in a probe subprocess"
        )


def tpu_topology_mesh(topology: str = "v5e:2x4", axis_names=("data",),
                      shape=None):
    """An n-chip TPU Mesh from an AOT topology description — no multi-chip
    hardware required (``jax.experimental.topologies``).  Programs built
    on this mesh can be ``.lower().compile()``d (not run) to inspect what
    the real TPU compiler does at scale."""
    import numpy as np
    from jax.experimental import topologies
    from jax.sharding import Mesh

    _probe_tpu_topology(topology)
    topo = topologies.get_topology_desc(platform="tpu", topology_name=topology)
    devs = np.array(topo.devices)
    if shape is None:
        shape = (devs.size,) if len(axis_names) == 1 else None
    return Mesh(devs.reshape(shape), axis_names)


def comm_schedule_ir(
    params,
    *,
    bucket_bytes: int | None = None,
    axis: str = "data",
    prim: str = "psum",
):
    """The bucketed grad-sync order as schedule IR (``ScheduleIR``,
    kind="grad-sync"): one tick per bucket, buckets planned from the
    param tree by the SAME planner the traced step uses
    (``native.plan_buckets``), so the SL302 traced-count check catches
    the step and the plan diverging (e.g. the all-reduce combiner
    re-merging buckets, or a refactor dropping the coalescing).

    ``bucket_bytes=None`` means leaf-sized buckets (one psum per leaf).
    Attached by ``make_train_step`` as ``step.comm_schedule(params)`` —
    a builder, not a constant, because the partition depends on the
    param tree the step is eventually called with.
    """
    import jax

    from distributeddataparallel_tpu import native
    from distributeddataparallel_tpu.analysis.schedule_lint import (
        grad_sync_schedule_ir,
    )

    leaves = jax.tree.leaves(params)
    if bucket_bytes is None:
        n_buckets = len(leaves)
    else:
        n_buckets = len(native.plan_buckets(
            [l.size * l.dtype.itemsize for l in leaves], bucket_bytes
        ))
    return grad_sync_schedule_ir(n_buckets, axis=axis, prim=prim)


def grad_sync_schedule_evidence(
    *,
    topology: str = "v5e:2x4",
    n_layers: int = 8,
    d_model: int = 2048,
    batch_per_chip: int = 32,
    bucket_bytes: int | None = None,
    chain: bool = True,
    options: dict | None = None,
    return_hlo: bool = False,
) -> dict:
    """AOT-compile a DP grad-sync step for a multi-chip TPU topology and
    report the scheduled overlap (``schedule_report``).

    The program is the DDP kernel in miniature: an ``n_layers`` MLP
    forward+backward with per-bucket chained pmean of the gradients —
    one bucket per layer by default (``bucket_bytes=None`` → leaf-sized
    buckets), matching the granularity DDP's Reducer sees.  With
    ``chain=False`` AND ``options={}`` (default compiler options: no
    async conversion, combiner on) the same program shows the stock-XLA
    failure mode — the combiner merges everything into one post-backward
    all-reduce — for comparison.  ``options=None`` means the full
    ``OVERLAP_COMPILER_OPTIONS``.
    """
    import jax
    import jax.numpy as jnp
    from jax import lax
    from jax.sharding import PartitionSpec as P

    from distributeddataparallel_tpu.parallel.data_parallel import (
        bucket_gradients,
    )

    mesh = tpu_topology_mesh(topology)
    n_chips = mesh.devices.size

    def step(w, x):
        def loss(w, x):
            h = x
            for wi in w:
                h = jnp.tanh(h @ wi)
            return jnp.sum(h.astype(jnp.float32) ** 2)

        g = jax.grad(loss)(w, x)
        if chain:
            bb = bucket_bytes or (d_model * d_model * 2)  # one leaf/bucket
            g = bucket_gradients(g, "data", bucket_bytes=bb, chain=True)
        else:
            g = jax.tree.map(lambda t: lax.pmean(t, "data"), g)
        return g

    fn = jax.jit(
        jax.shard_map(
            step, mesh=mesh, in_specs=(P(), P("data")), out_specs=P(),
            check_vma=False,
        )
    )
    w = [
        jax.ShapeDtypeStruct((d_model, d_model), jnp.bfloat16)
        for _ in range(n_layers)
    ]
    x = jax.ShapeDtypeStruct((batch_per_chip * n_chips, d_model), jnp.bfloat16)
    txt = (
        fn.lower(w, x)
        .compile(
            compiler_options=dict(
                OVERLAP_COMPILER_OPTIONS if options is None else options
            )
        )
        .as_text()
    )
    rep = validate_schedule_parse(
        schedule_report(txt), txt, where="grad_sync_schedule_evidence"
    )
    rep.update(
        {
            "topology": topology,
            "n_chips": n_chips,
            "compiler": compiler_stamp(),
            "config": {
                "n_layers": n_layers,
                "d_model": d_model,
                "batch_per_chip": batch_per_chip,
                "chain": chain,
                "bucket_bytes": bucket_bytes,
            },
        }
    )
    if return_hlo:
        rep["hlo_text"] = txt
    return rep


def train_step_schedule_evidence(
    *,
    model: str = "gpt2",
    topology: str = "v5e:2x4",
    per_chip_batch: int | None = None,
    seq_len: int | None = None,
    attn_impl: str = "xla",
    grad_compress: str | None = None,
    return_hlo: bool = False,
) -> dict:
    """AOT-compile the REAL ``make_train_step(..., overlap=True)`` for a
    multi-chip TPU topology and report the scheduled overlap — the
    model-scale evidence VERDICT r4 item 1 asked for (the r1-r4 numbers
    came from an 8-layer MLP proxy whose backward fusion structure says
    nothing about remat + scanned layers + a 50257-wide tied head).

    - ``model="gpt2"``: the bench's GPT-2 124M config (12 unrolled
      layers, adamw) — per-leaf/bucketed reduction at top level.
    - ``model="llama"``: the bench's Llama-0.6B-class config (GQA, RoPE,
      SwiGLU, remat + scanned layers, sgd+momentum) with
      ``grad_sync_axis`` — the per-layer reduction fires INSIDE the
      backward scan body (``sync_grad_in_backward``), the only placement
      the async scheduler can overlap for a scanned stack; the step
      skips those leaves via ``presynced``.

    The report is ``schedule_report`` (while-loop aware, scan trips
    counted at the model's layer count) + parse validation + compiler
    stamp + the exact model/step config.  Raises
    ``ScheduleEvidenceError`` on unparseable HLO and propagates compile
    failures — callers (bench/_run, tests) decide how to degrade.
    """
    import jax
    import jax.numpy as jnp
    import optax

    from distributeddataparallel_tpu.models.transformer import (
        TransformerLM,
        gpt2_124m,
        llama3_8b,
    )
    from distributeddataparallel_tpu.ops import lm_cross_entropy
    from distributeddataparallel_tpu.training.state import TrainState
    from distributeddataparallel_tpu.training.train_step import (
        make_train_step,
    )

    mesh = tpu_topology_mesh(topology)
    n_chips = mesh.devices.size
    if model == "gpt2":
        per_chip_batch = per_chip_batch or 8
        seq_len = seq_len or 1024
        cfg = gpt2_124m(
            max_seq_len=seq_len, dtype=jnp.bfloat16, attn_impl=attn_impl
        )
        tx = optax.adamw(3e-4)
        presynced = None
        trips = None
    elif model == "llama":
        per_chip_batch = per_chip_batch or 4
        seq_len = seq_len or 2048
        cfg = llama3_8b(
            num_layers=8, d_model=2048, d_ff=7168, num_heads=16,
            num_kv_heads=4, vocab_size=32000, max_seq_len=seq_len,
            attn_impl=attn_impl, grad_sync_axis="data",
            grad_sync_compress=grad_compress,
        )
        tx = optax.sgd(1e-3, momentum=0.9)
        presynced = lambda p: p[0] == "layers"  # noqa: E731
        trips = {"": cfg.num_layers}
    else:
        raise ValueError(f"model must be 'gpt2' or 'llama', got {model!r}")

    lm = TransformerLM(cfg)

    def loss_fn(params, batch, rng):
        toks = batch["tokens"]
        logits = lm.apply({"params": params}, toks[:, :-1])
        return lm_cross_entropy(logits, toks[:, 1:]), {}

    def make_state():
        params = lm.init(
            jax.random.PRNGKey(0), jnp.zeros((1, seq_len), jnp.int32)
        )["params"]
        return TrainState.create(apply_fn=None, params=params, tx=tx)

    state_sds = jax.eval_shape(make_state)
    batch_sds = {
        "tokens": jax.ShapeDtypeStruct(
            (per_chip_batch * n_chips, seq_len + 1), jnp.int32
        )
    }
    rng_sds = jax.ShapeDtypeStruct((2,), jnp.uint32)

    step = make_train_step(
        loss_fn, mesh=mesh, overlap=True, presynced=presynced,
        grad_compress=grad_compress,
    )
    import time

    t0 = time.perf_counter()
    txt = (
        step.lower(state_sds, batch_sds, rng_sds)
        .compile(compiler_options=dict(OVERLAP_COMPILER_OPTIONS))
        .as_text()
    )
    compile_s = round(time.perf_counter() - t0, 1)
    rep = validate_schedule_parse(
        schedule_report(txt, while_trip_counts=trips),
        txt,
        where=f"train_step_schedule_evidence({model})",
    )
    # Exact payload accounting: sync collectives execute once each in
    # the ENTRY schedule, so sync_collective_bytes / gradient-WIRE-bytes
    # is exact; async_bytes_frac is approximate (fusion-wrapper clones
    # can repeat a payload on the async side).  Under the bf16 comm hook
    # the wire carries 2 B/elem regardless of param dtype — dividing by
    # f32 bytes would flatter the async share 2x.
    grad_bytes = sum(
        l.size * l.dtype.itemsize
        for l in jax.tree.leaves(state_sds.params)
    )
    wire_bytes = (
        sum(2 * l.size for l in jax.tree.leaves(state_sds.params))
        if grad_compress == "bf16"
        else grad_bytes
    )
    rep["grad_bytes"] = grad_bytes
    rep["grad_wire_bytes"] = wire_bytes
    rep["async_frac_of_grad_bytes"] = round(
        max(0.0, 1.0 - rep["sync_collective_bytes"] / wire_bytes), 4
    )
    rep.update(
        {
            "model": model,
            "topology": topology,
            "n_chips": n_chips,
            "compiler": compiler_stamp(),
            "compile_s": compile_s,
            "config": {
                "per_chip_batch": per_chip_batch,
                "seq_len": seq_len,
                "attn_impl": attn_impl,
                "num_layers": cfg.num_layers,
                "scan_layers": cfg.scan_layers,
                "remat": cfg.remat,
                "grad_sync_axis": cfg.grad_sync_axis,
                "grad_compress": grad_compress,
            },
        }
    )
    if return_hlo:
        rep["hlo_text"] = txt
    return rep


def grad_sync_schedule_pair(**kwargs) -> dict:
    """The chain-vs-stock evidence pair, packaged for artifacts.

    One definition shared by the dryrun (MULTICHIP_PROBES.json) and the
    bench (BENCH_r{N}.json) so the two recorded protocols cannot drift.
    Raises if no TPU compiler is reachable — callers decide how to
    degrade.
    """
    sched = grad_sync_schedule_evidence(chain=True, **kwargs)
    # True stock contrast: per-leaf pmean under DEFAULT compiler options
    # (combiner on, no async conversion) — round 5 added the combiner-off
    # flag to OVERLAP_COMPILER_OPTIONS, which would otherwise leak the
    # overlap design into the "stock" side of the pair.
    stock = grad_sync_schedule_evidence(chain=False, options={}, **kwargs)
    keys = (
        "n_async_windows", "n_sync_collectives",
        "overlapped_compute_cycles", "total_compute_cycles",
        "overlapped_frac_of_compute", "topology", "n_chips", "compiler",
    )
    return {
        "tpu_schedule": {k: sched[k] for k in keys},
        "tpu_schedule_stock_xla": {
            k: stock[k]
            for k in ("n_async_windows", "overlapped_frac_of_compute")
        },
    }


def cpu_fabric_note() -> dict:
    """Machine-checked statement of why overlap cannot appear on the CPU
    test mesh: single-core fabric + synchronous-only CPU collectives.
    Returned as data so dryrun/bench artifacts carry the evidence."""
    import os

    import jax

    note = {
        "physical_cores": len(os.sched_getaffinity(0)),
        "claim": (
            "XLA:CPU lowers collectives as synchronous all-reduce (no "
            "start/done split, no async conversion pass), and the virtual "
            "8-device mesh time-slices one physical core where "
            "inter-device reduction is itself CPU work on that core — "
            "step_time >= compute + comm by construction, so "
            "overlap_frac=0.0 measures the fabric, not the framework. "
            "See parallel/overlap.py and OVERLAP.md for the TPU-schedule "
            "demonstration of the property."
        ),
    }
    # Verify the sync-only claim against the live compiler when this
    # process is on the CPU backend (cheap: tiny program).
    try:
        if jax.default_backend() == "cpu" and len(jax.devices()) > 1:
            import numpy as np
            import jax.numpy as jnp
            from jax import lax
            from jax.sharding import Mesh, PartitionSpec as P

            n = len(jax.devices())
            m = Mesh(np.array(jax.devices()), ("d",))
            f = jax.jit(
                jax.shard_map(
                    lambda t: lax.psum(t, "d"), mesh=m, in_specs=P(),
                    out_specs=P(), check_vma=False,
                )
            )
            txt = f.lower(jnp.ones((128,), jnp.float32)).compile().as_text()
            note["cpu_hlo_sync_allreduce"] = " all-reduce(" in txt
            note["cpu_hlo_async_allreduce"] = "all-reduce-start" in txt
    # ddplint: allow[broad-except] — evidence gathering; failure is recorded
    except Exception as exc:  # pragma: no cover - evidence gathering only
        note["verify_error"] = repr(exc)
    return note
