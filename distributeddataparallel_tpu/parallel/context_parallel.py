"""Context parallelism: ring attention over a sequence mesh axis.

The reference has no attention model and no sequence dimension at all
(SURVEY.md §5 "Long-context"); this module is the framework's long-context
scaling path, built the TPU way:

- The sequence dimension is sharded across a ``seq`` mesh axis: each
  device holds a (B, S/N, H, D) slice of q, k, v.
- **Ring attention** (Liu et al., arXiv 2310.01889 pattern): kv chunks
  rotate around the ring with ``lax.ppermute`` over ICI while each device
  accumulates blockwise attention of its local queries against the
  visiting chunk using the same online-softmax update as the flash kernel
  (``ops.pallas_attention``) — the full (S, S) score matrix never exists,
  and per-device memory stays O(S/N).
- XLA overlaps the ppermute transfer of chunk s+1 with the attention
  compute of chunk s (the latency-hiding scheduler sees independent
  DMA/compute chains), which is the property that makes the ring scale.
- Causal masking uses *global* offsets derived from ``lax.axis_index``,
  so cross-chunk blocks mask correctly; fully-masked visiting chunks
  still traverse the ring (uniform schedule) but their contribution is
  exactly zero.

``ring_attention`` is a collective op: it must run inside ``shard_map``
with ``axis_name`` bound.  ``make_cp_train_step`` wires it (together with
data parallelism on a second axis) into a compiled LM training step where
activations are sequence-sharded end to end — embeddings, norms, and MLPs
are per-token and need no communication; attention is the one collective.
"""

from __future__ import annotations

import functools
from typing import Any, Callable

import jax
import jax.numpy as jnp
from jax import lax
from jax.sharding import Mesh, PartitionSpec as P

from distributeddataparallel_tpu.ops.attention import NEG_INF, causal_mask_bias

Pytree = Any


def _flash_ring_fwd_impl(q, k, v, axis_name: str, interpret: bool):
    """Ring forward where each hop's block is the PALLAS flash kernel.

    Hop 0 runs the causal diagonal; later hops run the visiting chunk
    unmasked (its keys are strictly earlier) and wrapped chunks (strictly
    later keys) are zeroed by forcing their lse to -inf before the
    online-softmax merge of normalized partials:
    ``o = Σ o_i · exp(lse_i - logaddexp(lse…))``.
    Returns ``(out, lse)`` with lse (B, H, S) f32 — the backward's
    global row statistics.
    """
    from distributeddataparallel_tpu.ops.pallas_attention import (
        _flash_fwd_impl,
    )

    n = lax.psum(1, axis_name)
    idx = lax.axis_index(axis_name)
    B, S, H, D = q.shape
    out, lse8 = _flash_fwd_impl(q, k, v, causal=True, interpret=interpret)
    lse = lse8[:, 0, :].reshape(B, H, S)
    of = out.astype(jnp.float32)
    perm = [(i, (i + 1) % n) for i in range(n)]

    def hop(carry, s):
        kc, vc, of, lse = carry
        kc = lax.ppermute(kc, axis_name, perm)
        vc = lax.ppermute(vc, axis_name, perm)
        oh, lseh8 = _flash_fwd_impl(
            q, kc, vc, causal=False, interpret=interpret
        )
        lseh = lseh8[:, 0, :].reshape(B, H, S)
        # After s hops this device holds chunk idx - s; wrapped (future)
        # chunks contribute nothing.
        lseh = jnp.where(idx - s >= 0, lseh, NEG_INF)
        lse_new = jnp.logaddexp(lse, lseh)
        w_old = jnp.exp(lse - lse_new).transpose(0, 2, 1)[..., None]
        w_new = jnp.exp(lseh - lse_new).transpose(0, 2, 1)[..., None]
        of = of * w_old + oh.astype(jnp.float32) * w_new
        return (kc, vc, of, lse_new), None

    (_, _, of, lse), _ = lax.scan(
        hop, (k, v, of, lse), jnp.arange(1, n)
    )
    return of.astype(q.dtype), lse


@functools.partial(jax.custom_vjp, nondiff_argnums=(3, 4))
def flash_ring_attention(
    q, k, v, axis_name: str, interpret: bool = False
):
    """Causal ring attention whose per-hop block math runs in the Pallas
    flash kernel (``ops.pallas_attention``) instead of XLA einsums —
    the long-context CP path at flash speed (README r2 admitted the ring
    couldn't use the kernel; this closes it).

    Same contract as ``ring_attention``: local shards (B, S/N, H, D)
    inside shard_map, kv already expanded to the query head count.
    The backward is the standard ring-flash scheme: per hop, the saved
    GLOBAL (out, lse) make ``exp(s - lse)`` the exact softmax slice for
    the visiting chunk, so the per-chunk Pallas backward kernels emit
    exact dq/dk/dv pieces; dk/dv ride the ring with their chunk and one
    final hop returns them to the owner.
    """
    out, _ = _flash_ring_fwd_impl(q, k, v, axis_name, interpret)
    return out


def _flash_ring_fwd(q, k, v, axis_name, interpret):
    out, lse = _flash_ring_fwd_impl(q, k, v, axis_name, interpret)
    return out, (q, k, v, out, lse)


def _flash_ring_bwd(axis_name, interpret, res, do):
    from distributeddataparallel_tpu.ops.pallas_attention import (
        _bwd as flash_bwd,
    )

    q, k, v, out, lse = res
    n = lax.psum(1, axis_name)
    idx = lax.axis_index(axis_name)
    B, S, H, D = q.shape
    lse8 = jnp.broadcast_to(
        lse.reshape(B * H, 1, S), (B * H, 8, S)
    )
    # Hop 0: own chunk, causal diagonal.
    dq, dk, dv = flash_bwd(True, interpret, (q, k, v, out, lse8), do)
    perm = [(i, (i + 1) % n) for i in range(n)]

    def hop(carry, s):
        kc, vc, dkc, dvc, dq = carry
        kc = lax.ppermute(kc, axis_name, perm)
        vc = lax.ppermute(vc, axis_name, perm)
        dkc = lax.ppermute(dkc, axis_name, perm)
        dvc = lax.ppermute(dvc, axis_name, perm)
        dq_h, dk_h, dv_h = flash_bwd(
            False, interpret, (q, kc, vc, out, lse8), do
        )
        live = (idx - s >= 0).astype(dq.dtype)
        dq = dq + dq_h * live
        dkc = dkc + dk_h.astype(dkc.dtype) * live
        dvc = dvc + dv_h.astype(dvc.dtype) * live
        return (kc, vc, dkc, dvc, dq), None

    (_, _, dkc, dvc, dq), _ = lax.scan(
        hop, (k, v, dk, dv, dq), jnp.arange(1, n)
    )
    # Chunks sit one hop short of home after n-1 rotations; the final
    # rotation delivers each chunk's accumulated gradient to its owner.
    dk = lax.ppermute(dkc, axis_name, perm)
    dv = lax.ppermute(dvc, axis_name, perm)
    return dq, dk, dv


flash_ring_attention.defvjp(_flash_ring_fwd, _flash_ring_bwd)


def ring_attention(
    q: jnp.ndarray,
    k: jnp.ndarray,
    v: jnp.ndarray,
    *,
    axis_name: str,
    causal: bool = True,
    impl: str = "auto",
) -> jnp.ndarray:
    """Blockwise ring attention over the ``axis_name`` mesh axis.

    q, k, v: local shards (B, S_local, H, D); the global sequence is the
    concatenation of shards in axis order.  Returns the local (B, S_local,
    H, D) output shard — numerically identical (up to fp accumulation
    order) to slicing full attention over the gathered sequence.

    ``impl``: 'auto' uses the Pallas flash kernel per kv-hop
    (``flash_ring_attention``) when the local shapes support it and the
    kernel probe-compiles, 'pallas' forces it, 'xla' keeps the einsum
    blocks below.  Only causal attention takes the kernel path (the
    ring's wrap masking assumes it).
    """
    if impl in ("auto", "pallas") and causal:
        from distributeddataparallel_tpu.ops.attention import _flash_compiles
        from distributeddataparallel_tpu.ops.pallas_attention import supported

        if supported(q, k, v) and k.shape[2] == q.shape[2]:
            # Probe BOTH causal variants: hop 0 runs the causal kernels,
            # every later hop the non-causal ones — a shape passing only
            # the causal probe would still die at jit time in the ring.
            if impl == "pallas" or (
                _flash_compiles(q, k, v, True)
                and _flash_compiles(q, k, v, False)
            ):
                return flash_ring_attention(q, k, v, axis_name)
        elif impl == "pallas":
            raise ValueError(
                f"pallas ring attention unsupported for shapes "
                f"q={q.shape} kv={k.shape} on {jax.default_backend()}"
            )
    n = lax.psum(1, axis_name)
    idx = lax.axis_index(axis_name)
    B, S, H, D = q.shape
    scale = 1.0 / jnp.sqrt(jnp.asarray(D, jnp.float32))
    q_off = idx * S

    qf = q.astype(jnp.float32)

    def accumulate(stats, kc, vc, src):
        """Online-softmax update of (m, l, acc) with the visiting chunk
        whose global ring position is `src`."""
        m, l, acc = stats
        logits = (
            jnp.einsum("bqhd,bkhd->bhqk", qf, kc.astype(jnp.float32)) * scale
        )
        if causal:
            logits = logits + causal_mask_bias(
                S, S, q_offset=q_off, kv_offset=src * S
            )[None, None]
        m_cur = jnp.max(logits, axis=-1)          # (B, H, S)
        m_new = jnp.maximum(m, m_cur)
        p = jnp.exp(logits - m_new[..., None])    # (B, H, S, S)
        corr = jnp.exp(m - m_new)
        l_new = corr * l + jnp.sum(p, axis=-1)
        acc_new = acc * corr[..., None] + jnp.einsum(
            "bhqk,bkhd->bhqd", p, vc.astype(jnp.float32)
        )
        return m_new, l_new, acc_new

    perm = [(i, (i + 1) % n) for i in range(n)]

    def step(carry, s):
        kc, vc, stats = carry
        # Rotate first (s >= 1): n-1 hops total, no dead final rotation.
        kc = lax.ppermute(kc, axis_name, perm)
        vc = lax.ppermute(vc, axis_name, perm)
        # After s hops this device holds the chunk from position idx - s.
        stats = accumulate(stats, kc, vc, (idx - s) % n)
        return (kc, vc, stats), None

    m0 = jnp.full((B, H, S), NEG_INF, jnp.float32)
    l0 = jnp.zeros((B, H, S), jnp.float32)
    acc0 = jnp.zeros((B, H, S, D), jnp.float32)
    stats = accumulate((m0, l0, acc0), k, v, idx)  # own chunk, hop 0
    (_, _, (m, l, acc)), _ = lax.scan(
        step, (k, v, stats), jnp.arange(1, n)
    )
    # Rows with no visible kv (can't happen for causal self-attention, but
    # guard against l == 0 for safety).
    l_safe = jnp.where(l == 0.0, 1.0, l)
    out = (acc / l_safe[..., None]).transpose(0, 2, 1, 3)  # (B, S, H, D)
    return out.astype(q.dtype)


def ulysses_attention(
    q: jnp.ndarray,
    k: jnp.ndarray,
    v: jnp.ndarray,
    *,
    axis_name: str,
    causal: bool = True,
    impl: str = "auto",
) -> jnp.ndarray:
    """All-to-all (DeepSpeed-Ulysses-style) sequence-parallel attention.

    The dual of ``ring_attention`` over the same sequence-sharded layout
    (local shards (B, S/N, H, D), global sequence = shards in axis
    order), trading N-1 ``ppermute`` hops for two ``all_to_all``s:

    1. all-to-all scatters the HEAD dim and gathers the SEQUENCE dim —
       each device now holds ALL tokens for H/N of the heads;
    2. ordinary full-sequence attention runs locally per head group —
       on TPU this is the framework's own Pallas flash kernel
       (``ops.attention.attention``), which the ring path cannot use
       because no device ever sees the whole sequence;
    3. the inverse all-to-all restores the sequence-sharded layout.

    Trade-offs vs the ring: communication is 2 all-to-alls of the
    activations regardless of N (the ring moves the whole KV cache N-1
    times, overlapped), but parallelism is capped at the head count
    (H % N == 0).  GQA: when the kv head count divides N too, kv travels
    at its own (smaller) head count and the local attention consumes it
    natively; otherwise kv heads are expanded before the exchange.

    Must run inside ``shard_map`` with ``axis_name`` bound.  RoPE /
    positional lookups happen BEFORE this op with global positions
    (``cp_positions``), exactly as for the ring path.
    """
    n = lax.psum(1, axis_name)
    B, S, H, D = q.shape
    Hkv = k.shape[2]
    if H % n:
        raise ValueError(
            f"ulysses requires num_heads % axis size == 0, got {H} % {n}"
        )
    if Hkv % n:
        # GQA with a kv head count the axis doesn't divide: replicate kv
        # heads to lcm(Hkv, n) — the smallest count the all_to_all can
        # split — not all the way to H.  rep always divides the GQA group
        # size (H % n == 0 forces it), so the local attention still sees
        # a valid grouped layout, and q-head j keeps mapping to its
        # original kv head j // (H/Hkv).
        import math

        from distributeddataparallel_tpu.ops.attention import repeat_kv

        rep = n // math.gcd(Hkv, n)
        assert H % (Hkv * rep) == 0, (H, Hkv, n)
        k = repeat_kv(k, rep)
        v = repeat_kv(v, rep)
    # Scatter heads / gather sequence: (B, S/N, H, D) -> (B, S, H/N, D).
    # Received shards concatenate in axis order, so the gathered sequence
    # is in global order and q-head block j pairs with kv-head block j
    # (head groups stay contiguous because H/N is a multiple of the GQA
    # group size whenever Hkv % N == 0).
    from distributeddataparallel_tpu.ops.attention import attention

    a2a = lambda x: lax.all_to_all(
        x, axis_name, split_axis=2, concat_axis=1, tiled=True
    )
    out = attention(a2a(q), a2a(k), a2a(v), causal=causal, impl=impl)
    # Inverse: scatter sequence / gather heads -> (B, S/N, H, D).
    return lax.all_to_all(
        out, axis_name, split_axis=1, concat_axis=2, tiled=True
    )


def cp_positions(seq_len_local: int, axis_name: str) -> jnp.ndarray:
    """Global token positions of this device's sequence shard (for RoPE /
    learned positional lookups inside shard_map)."""
    return lax.axis_index(axis_name) * seq_len_local + jnp.arange(
        seq_len_local
    )


def make_cp_train_step(
    loss_fn: Callable,
    *,
    mesh: Mesh,
    data_axis: str = "data",
    seq_axis: str = "seq",
    donate: bool = True,
    **kwargs,
):
    """Compiled train step with DP × CP sharding.

    ``loss_fn(params, batch, rng) -> (loss, aux)`` runs per mesh position
    on a batch whose leaves are sharded (batch-dim → ``data_axis``,
    seq-dim → ``seq_axis``); inside it the model must use collective
    attention (``TransformerConfig.cp_axis = seq_axis``) so the sharded
    sequence attends globally.  The per-position mean loss is weighted
    uniformly per token, so gradients are pmean'd over BOTH axes —
    equivalent to global-batch DP on the full sequence.

    Batches come pre-split by the host into {"inputs", "targets"} (the
    next-token shift crosses shard boundaries, so it must happen before
    sharding — see ``data.loader.shard_lm_batch``).

    Thin wrapper over ``training.train_step.make_train_step(cp_axis=...)``
    — every DP feature (gradient accumulation, bucketing, ZeRO-1,
    grad_sync=False) composes with CP through ``kwargs``.
    """
    from distributeddataparallel_tpu.training.train_step import make_train_step

    return make_train_step(
        loss_fn, mesh=mesh, axis_name=data_axis, cp_axis=seq_axis,
        donate=donate, **kwargs,
    )


def make_cp_eval_step(
    metric_fn: Callable,
    *,
    mesh: Mesh,
    data_axis: str = "data",
    seq_axis: str = "seq",
    masked: bool = False,
    param_specs=None,
):
    """Jit'd DP×CP eval: ``metric_fn(params, batch) -> dict`` per position,
    pmean'd over both axes.

    ``masked=True``: exact evaluation over sampler-padded batches.  The
    batch is ``{"inputs", "targets", "valid"}`` (``shard_lm_batch`` with a
    ``valid`` row mask); metric_fn must return PER-ROW vectors over the
    local (rows, seq-chunk) shard.  Per-row values are first pmean'd over
    the seq axis (chunks are equal-length, so this is the exact global
    per-row mean), then masked-mean'd over the data axis so padded
    duplicate rows contribute nothing.  Returns ``(metrics, count)`` like
    ``make_eval_step(masked=True)``.
    """

    def _eval(params: Pytree, batch: Pytree):
        if masked:
            batch = dict(batch)
            mask = batch.pop("valid")
        metrics = metric_fn(params, batch)
        if masked:
            from distributeddataparallel_tpu.parallel.data_parallel import (
                masked_tree_mean,
            )

            return masked_tree_mean(
                metrics, mask, data_axis, seq_axis=seq_axis
            )
        return jax.tree.map(
            lambda m: lax.pmean(lax.pmean(m, data_axis), seq_axis), metrics
        )

    if masked:
        batch_specs: Any = {
            "inputs": P(data_axis, seq_axis),
            "targets": P(data_axis, seq_axis),
            "valid": P(data_axis),
        }
    else:
        batch_specs = P(data_axis, seq_axis)
    sharded = jax.shard_map(
        _eval,
        mesh=mesh,
        in_specs=(param_specs if param_specs is not None else P(),
                  batch_specs),
        out_specs=P(),
        check_vma=False,
    )
    return jax.jit(sharded)
