"""jax version compatibility shims (installed by the package __init__).

The framework targets the current jax public API; this container pins
jax 0.4.37, where three of those surfaces don't exist yet.  Per the
repo's no-new-deps rule the gap is bridged here, in one place, instead
of scattering version branches through every call site:

- ``jax.shard_map`` — public alias landed after 0.4.37; the same
  function lives at ``jax.experimental.shard_map.shard_map`` with the
  replication-check kwarg under its old name (``check_rep``, later
  renamed ``check_vma`` with the varying-manual-axes rework).  The shim
  adapts the new-style call (keyword mesh/specs, ``check_vma=``) onto
  the experimental entry point.
- ``lax.axis_size`` — newer trace-time axis-size lookup; 0.4.37 exposes
  the same fact through the axis env (``get_axis_env().axis_size``),
  still static at trace time, which is what the bucketed all-reduce's
  static mean divisor depends on.
- ``jax.tree.flatten_with_path`` — the ``jax.tree`` namespace predates
  its path variants here; ``jax.tree_util.tree_flatten_with_path`` is
  the same function.

Each shim is gated on ``hasattr``, so on a newer jax this module is a
no-op and the native implementations win.
"""

from __future__ import annotations

import jax
from jax import lax

if not hasattr(jax, "shard_map"):
    from jax.experimental.shard_map import shard_map as _shard_map

    def shard_map(f, *, mesh, in_specs, out_specs, check_vma=None, **kw):
        if check_vma is not None:
            kw["check_rep"] = check_vma
        return _shard_map(
            f, mesh=mesh, in_specs=in_specs, out_specs=out_specs, **kw
        )

    jax.shard_map = shard_map

if not hasattr(lax, "axis_size"):

    def axis_size(axis_name):
        from jax._src.core import get_axis_env

        names = (
            axis_name if isinstance(axis_name, (tuple, list))
            else (axis_name,)
        )
        size = 1
        for name in names:
            size *= get_axis_env().axis_size(name)
        return size

    lax.axis_size = axis_size

if not hasattr(jax.tree, "flatten_with_path"):
    jax.tree.flatten_with_path = jax.tree_util.tree_flatten_with_path


def configure_cpu_devices(n: int) -> None:
    """Force ``n`` fake CPU devices, portable across jax versions.

    Newer jax has the ``jax_num_cpu_devices`` config option; 0.4.37 only
    honors the pre-backend-init XLA flag.  Either way this must run
    before the first device query creates the CPU client (the callers —
    conftest, ``dpp.py --device cpu``, spawned test workers — all run it
    at interpreter startup).
    """
    import os
    import re

    jax.config.update("jax_platforms", "cpu")
    try:
        jax.config.update("jax_num_cpu_devices", n)
    except AttributeError:
        # REPLACE any inherited count rather than keeping it: a child
        # process asking for 4 devices under a parent that exported 8
        # (the elastic-resume tests) must win.
        flags = re.sub(
            r"--xla_force_host_platform_device_count=\d+",
            "",
            os.environ.get("XLA_FLAGS", ""),
        )
        os.environ["XLA_FLAGS"] = (
            flags + f" --xla_force_host_platform_device_count={n}"
        ).strip()
