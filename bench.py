#!/usr/bin/env python
"""Benchmark harness: prints ONE JSON line with the headline metric.

Headline (BASELINE.md config 3): ResNet-50 ImageNet-shape data-parallel
training throughput, img/s/chip, target >=70% of A100 NCCL-DDP per-chip
throughput.  A100 DDP ResNet-50 (mixed precision, per-chip) is ~2500
img/s; vs_baseline is measured against 0.7 * 2500 = 1750 img/s/chip.

The JSON line also carries an ``extras`` payload (BASELINE config 4 +
VERDICT r1 items 3/10): GPT-2 124M LM tokens/s/chip with the Pallas
flash kernel vs the XLA attention path (winner recorded), device kind,
batch geometry, and per-step time distribution.

Runs on however many chips are visible (the driver provides one real TPU
chip); DP sharding is exercised whenever device_count > 1.
"""

from __future__ import annotations

import json
import time

A100_DDP_RESNET50_IMG_S = 2500.0  # per-chip, AMP, the BASELINE §3 yardstick
TARGET_FRACTION = 0.70


#: Peak bf16 FLOPS / HBM bandwidth by device kind — the MFU and
#: HBM-utilization denominators.  Unknown kinds fall back to v5e with
#: ``assumed: true`` recorded in the emitted JSON so the denominators
#: are never silently wrong on another backend.
_PEAKS = {
    "tpu v5 lite": (197e12, 819e9),
    "tpu v5e": (197e12, 819e9),
    "tpu v5p": (459e12, 2765e9),
    "tpu v4": (275e12, 1228e9),
}


def _device_peaks() -> dict:
    import jax

    kind = getattr(jax.devices()[0], "device_kind", "unknown").lower()
    for key, (flops, hbm) in _PEAKS.items():
        if key in kind:
            return {
                "device_kind": kind, "flops": flops, "hbm_bytes_s": hbm,
                "assumed": False,
            }
    return {
        "device_kind": kind, "flops": 197e12, "hbm_bytes_s": 819e9,
        "assumed": True,
    }


def _fence(state) -> float:
    """Force the whole step chain by reading a value computed from the
    updated params.  (block_until_ready on donated params is NOT a
    reliable fence on this runtime — donation aliasing can report the
    buffer ready early, which once inflated throughput ~35x.)"""
    import jax
    import jax.numpy as jnp

    leaf = jax.tree.leaves(state.params)[0]
    return float(jnp.sum(leaf.astype(jnp.float32)))


def _time_steps(step, state, batch, key, *, warmup: int, iters: int):
    """Run timed steps after warmup; returns (state, mean_s, dist_ms).

    The headline mean times ``iters`` back-to-back dispatches behind ONE
    value fence — fencing inside the timed region would insert a host
    round-trip (expensive through the driver's TPU tunnel) into every
    sample.  A second, shorter pass fences every 4 steps to get a
    per-step distribution; its samples carry ~RTT/4 overhead each and
    are reported separately from the headline.
    """
    for _ in range(warmup):
        state, _ = step(state, batch, key)
    f = _fence(state)
    assert f == f, "NaN params after warmup"

    t0 = time.perf_counter()
    for _ in range(iters):
        state, _ = step(state, batch, key)
    _fence(state)
    mean_s = (time.perf_counter() - t0) / iters

    chunk, chunks = 4, 3
    dist: list[float] = []
    for _ in range(chunks):
        t0 = time.perf_counter()
        for _ in range(chunk):
            state, _ = step(state, batch, key)
        _fence(state)
        dist.append((time.perf_counter() - t0) / chunk * 1e3)
    return state, mean_s, dist


def bench_resnet50() -> dict:
    import jax
    import jax.numpy as jnp
    import numpy as np
    import optax

    import distributeddataparallel_tpu as ddp
    from distributeddataparallel_tpu.data.loader import shard_batch
    from distributeddataparallel_tpu.models.resnet import ResNet50
    from distributeddataparallel_tpu.ops import cross_entropy_loss

    mesh = ddp.make_mesh(("data",))
    n_dev = len(jax.devices())

    model = ResNet50(num_classes=1000, dtype=jnp.bfloat16)
    image_shape = (224, 224, 3)
    per_chip_batch = 128

    rng = jax.random.PRNGKey(0)
    sample = jnp.zeros((1,) + image_shape, jnp.float32)
    # jit the init: eager flax init dispatches one op at a time, which is
    # minutes of round-trips through the driver's TPU tunnel.
    variables = jax.jit(model.init)(rng, sample)
    params = variables["params"]
    model_state = {k: v for k, v in variables.items() if k != "params"}

    def loss_fn(params, ms, batch, rng):
        logits, new_vars = model.apply(
            {"params": params, **ms}, batch["image"], train=True,
            mutable=list(ms.keys()),
        )
        return cross_entropy_loss(logits, batch["label"]), ({}, new_vars)

    state = ddp.TrainState.create(
        apply_fn=model.apply,
        params=params,
        tx=optax.sgd(0.1, momentum=0.9),
        model_state=model_state,
    )
    state = ddp.broadcast_params(state, mesh)
    step = ddp.make_train_step(loss_fn, mesh=mesh, with_model_state=True)

    B = per_chip_batch * n_dev
    npr = np.random.default_rng(0)
    batch = shard_batch(
        {
            "image": npr.normal(size=(B,) + image_shape).astype(np.float32),
            "label": npr.integers(0, 1000, size=(B,)).astype(np.int32),
        },
        mesh,
    )
    state, mean_s, dist = _time_steps(
        step, state, batch, jax.random.PRNGKey(1), warmup=4, iters=20
    )

    # End-to-end variant: the DataLoader feeds the step from host RAM
    # every step (threaded worker + prefetch — the input pipeline under
    # load, not a resident batch).  Same compiled step, same shapes.
    # Two numbers: the host pipeline alone (gather + collate rate), and
    # the full loader->device->step path.  In THIS environment the
    # latter crosses a network tunnel to the remote chip (~77 MB/batch),
    # so it measures tunnel bandwidth, not the framework — flagged via
    # h2d_note; on a real TPU VM the copy is local PCIe/DMA.
    from distributeddataparallel_tpu.data import DataLoader
    from distributeddataparallel_tpu.data.datasets import SyntheticClassification

    ds = SyntheticClassification(
        num_examples=B * 2, shape=image_shape, num_classes=1000, seed=1
    )
    def host_rate(dataset, augment=None) -> float:
        loader = DataLoader(
            dataset, per_replica_batch=per_chip_batch, mesh=mesh,
            shuffle=True, seed=0, device_feed=False, augment=augment,
        )
        rows = 0
        t0 = time.perf_counter()
        for epoch in range(2):
            loader.set_epoch(epoch)
            for b in loader:
                rows += b["image"].shape[0]
        return rows / (time.perf_counter() - t0)

    host_img_s = host_rate(ds)
    # u8 storage mode: same pipeline through the fused native C++
    # gather+normalize kernel (csrc) — the production input path for
    # image payloads (CIFAR stores u8).
    from distributeddataparallel_tpu import native

    ds_u8 = SyntheticClassification(
        num_examples=B * 2, shape=image_shape, num_classes=1000, seed=1,
        keep_u8=True,
    )
    host_u8_img_s = host_rate(ds_u8)
    # Full training-augmentation chain fused into the same native pass
    # (gather + RandomCrop + flip + normalize, csrc ddp_gather_augment_u8).
    from distributeddataparallel_tpu.data import CifarAugment

    host_u8_aug_img_s = host_rate(ds_u8, augment=CifarAugment())

    loader = DataLoader(
        ds, per_replica_batch=per_chip_batch, mesh=mesh, shuffle=True,
        seed=0, workers=1,
    )
    key = jax.random.PRNGKey(2)
    for b in loader:  # warm epoch (loader thread spin-up, no recompile)
        state, _ = step(state, b, key)
    _fence(state)
    steps = 0
    t0 = time.perf_counter()
    for epoch in range(1, 3):
        loader.set_epoch(epoch)
        for b in loader:
            state, _ = step(state, b, key)
            steps += 1
    _fence(state)
    e2e_s = (time.perf_counter() - t0) / max(steps, 1)

    return {
        "img_s_chip": round(per_chip_batch / mean_s, 2),
        # Roofline context (VERDICT r2 weak 4): ResNet-50 fwd at 224² is
        # ~4.1 GFLOPs/img, training ~3x that; utilization against the
        # device kind's bf16 peak (_device_peaks).
        "mfu_est": round(
            (per_chip_batch / mean_s) * 3 * 4.1e9 / _device_peaks()["flops"],
            4,
        ),
        "per_chip_batch": per_chip_batch,
        "step_ms_mean": round(mean_s * 1e3, 3),
        "step_ms_fenced_chunks": [round(t, 3) for t in dist],
        "host_pipeline_img_s": round(host_img_s, 1),
        # Label says what actually ran: without the built C++ library the
        # u8 path silently falls back to NumPy, which must not be
        # reported under a 'native' name.
        ("host_pipeline_u8_native_img_s" if native.available()
         else "host_pipeline_u8_numpy_img_s"): round(host_u8_img_s, 1),
        ("host_pipeline_u8_augment_native_img_s" if native.available()
         else "host_pipeline_u8_augment_numpy_img_s"):
            round(host_u8_aug_img_s, 1),
        "native_kernels": native.available(),
        "e2e_img_s_chip": round(per_chip_batch / e2e_s, 2),
        "e2e_step_ms": round(e2e_s * 1e3, 3),
        "e2e_steps": steps,
        "h2d_note": (
            "e2e pays host->device transfer; through this driver's "
            "network tunnel that dominates (not framework overhead — "
            "see host_pipeline_img_s for the input machinery's rate)"
        ),
    }


def _gpt2_setup(attn_impl: str, *, per_chip_batch: int = 8,
                seq_len: int = 1024, tx=None):
    """Shared GPT-2 124M DP fixture: (mesh, loss_fn, state, batch).

    Used by both the throughput and overlap sections so they measure the
    SAME workload (config, batch geometry, loss) and cannot diverge.
    """
    import jax
    import jax.numpy as jnp
    import numpy as np
    import optax

    import distributeddataparallel_tpu as ddp
    from distributeddataparallel_tpu.data.loader import shard_batch
    from distributeddataparallel_tpu.models import TransformerLM, gpt2_124m
    from distributeddataparallel_tpu.ops import lm_cross_entropy

    mesh = ddp.make_mesh(("data",))
    B = per_chip_batch * len(jax.devices())
    cfg = gpt2_124m(max_seq_len=seq_len, dtype=jnp.bfloat16,
                    attn_impl=attn_impl)
    model = TransformerLM(cfg)
    # init at full seq_len (the forced-pallas path rejects non-block-
    # aligned shapes); jit'd to avoid eager per-op tunnel round-trips.
    params = jax.jit(model.init)(
        jax.random.PRNGKey(0), jnp.zeros((1, seq_len), jnp.int32)
    )["params"]

    def loss_fn(params, batch, rng):
        toks = batch["tokens"]
        logits = model.apply({"params": params}, toks[:, :-1])
        return lm_cross_entropy(logits, toks[:, 1:]), {}

    state = ddp.TrainState.create(
        apply_fn=model.apply, params=params, tx=tx or optax.adamw(3e-4)
    )
    state = ddp.broadcast_params(state, mesh)
    npr = np.random.default_rng(0)
    batch = shard_batch(
        {"tokens": npr.integers(
            0, 50257, size=(B, seq_len + 1)
        ).astype(np.int32)},
        mesh,
    )
    return mesh, loss_fn, state, batch


def bench_gpt2() -> dict:
    """GPT-2 124M pure-DP LM step (BASELINE config 4): tokens/s/chip,
    measured once with the Pallas flash kernel and once with the XLA
    attention path; the winner is what users get from attn_impl='auto'."""
    import jax

    import distributeddataparallel_tpu as ddp

    N_PARAMS = 124.4e6  # GPT-2 124M
    seq_len = 1024
    results = {}
    # (impl, per-chip batch): the b16 pallas row is the MFU lever —
    # a bigger per-chip batch amortizes the non-matmul time (VERDICT r2
    # weak 4: b8 ran ~42% MFU with no roofline context reported).
    # (A per-chip-batch-16 pallas variant was measured in development
    # and did NOT raise MFU — 41.97% vs 42.88% at b8 — so the batch
    # lever is closed: the residual gap vs the llama section's ~53% is
    # the learned-positional/LayerNorm f32 VPU work and the
    # tied-embedding head.)
    pcb = 8
    for impl in ("pallas", "xla"):
        want_pallas = impl == "pallas" and jax.default_backend() == "tpu"
        mesh, loss_fn, state, batch = _gpt2_setup(
            "pallas" if want_pallas else "xla",
            per_chip_batch=pcb, seq_len=seq_len,
        )
        step = ddp.make_train_step(loss_fn, mesh=mesh)
        state, mean_s, dist = _time_steps(
            step, state, batch, jax.random.PRNGKey(1), warmup=3, iters=12
        )
        toks = pcb * seq_len / mean_s
        results[impl] = {
            "tokens_s_chip": round(toks, 1),
            "mfu_est": round(6 * N_PARAMS * toks / _device_peaks()["flops"], 4),
            "per_chip_batch": pcb,
            "step_ms_mean": round(mean_s * 1e3, 3),
            "step_ms_fenced_chunks": [round(t, 3) for t in dist],
            "ran_pallas": want_pallas,
        }
        if want_pallas:
            # MFU-gap decomposition (VERDICT r3 item 8): bucket the
            # compiled step's own estimated_cycles by trace scope.
            # Measured: the TIED head's d x V matmuls (fwd + transpose
            # grad into the embedding) are ~24% of all scheduled cycles
            # and the loss softmax ~9% — a third of the step on
            # vocab-width work the 6N MFU numerator largely miscredits
            # at 124M scale (V=50257 vs d=768; Llama-0.6B's smaller
            # head share is exactly why its mfu_est reads ~53%).
            # Experiments: untied head measured SLOWER (91.4 -> 94.6 ms
            # — same head FLOPs, 38M more params to update); RoPE
            # instead of learned positions gained ~1%.  The r3
            # attribution to f32 LayerNorms is refuted: norms measure
            # 0.07% of cycles.  Conclusion: ~44% mfu_est IS the 124M
            # tied-head ceiling; the decomposition below re-records
            # every round.
            from distributeddataparallel_tpu.parallel.overlap import (
                cycles_by_scope,
            )

            try:
                txt = (
                    step.lower(state, batch, jax.random.PRNGKey(1))
                    .compile().as_text()
                )
                decomp = cycles_by_scope(txt, strict=True, buckets={
                    "attention": (
                        "q_proj|k_proj|v_proj|out_proj|attn|flash|attention"
                    ),
                    "mlp": "/mlp/",
                    "norms": "ln_|norm",
                    "embed_lookup": "token_embed|pos_embed|lm_head",
                    "tied_head_matmuls": r"TransformerLM\)+/dot_general",
                    "loss_softmax": r"cross_entropy|log_softmax|jvp\(\)/",
                })
            except Exception as e:  # noqa: BLE001 - diagnostics only
                decomp = {"error": repr(e)}
            results[impl]["cycle_decomposition"] = decomp
        del state, step

    winner = max(results, key=lambda k: results[k]["tokens_s_chip"])
    return {
        "tokens_s_chip": results[winner]["tokens_s_chip"],
        "mfu_est": results[winner]["mfu_est"],
        "attn_winner": winner,
        "per_impl": results,
        "seq_len": seq_len,
    }


def bench_llama() -> dict:
    """Llama-family DP step (BASELINE config 5's model class, scaled to
    one chip): GQA 16q/4kv, RoPE, SwiGLU, remat + scanned layers, bf16 —
    the flash kernel consumes the grouped kv natively.  ~0.6B params;
    the full 8B memory story lives in MEMFIT.md."""
    import jax
    import jax.numpy as jnp
    import numpy as np
    import optax

    import distributeddataparallel_tpu as ddp
    from distributeddataparallel_tpu.data.loader import shard_batch
    from distributeddataparallel_tpu.models import TransformerLM, llama3_8b
    from distributeddataparallel_tpu.ops import lm_cross_entropy

    mesh = ddp.make_mesh(("data",))
    n_dev = len(jax.devices())
    per_chip_batch, seq_len = 4, 2048

    cfg = llama3_8b(
        num_layers=8, d_model=2048, d_ff=7168, num_heads=16, num_kv_heads=4,
        vocab_size=32000, max_seq_len=seq_len,
    )
    model = TransformerLM(cfg)
    params = jax.jit(model.init)(
        jax.random.PRNGKey(0), jnp.zeros((1, seq_len), jnp.int32)
    )["params"]
    n_params = sum(l.size for l in jax.tree.leaves(params))

    def loss_fn(params, batch, rng):
        toks = batch["tokens"]
        logits = model.apply({"params": params}, toks[:, :-1])
        return lm_cross_entropy(logits, toks[:, 1:]), {}

    state = ddp.TrainState.create(
        apply_fn=model.apply, params=params,
        tx=optax.sgd(1e-3, momentum=0.9),
    )
    state = ddp.broadcast_params(state, mesh)
    step = ddp.make_train_step(loss_fn, mesh=mesh)
    npr = np.random.default_rng(0)
    batch = shard_batch(
        {"tokens": npr.integers(
            0, 32000, size=(per_chip_batch * n_dev, seq_len + 1)
        ).astype(np.int32)},
        mesh,
    )
    state, mean_s, dist = _time_steps(
        step, state, batch, jax.random.PRNGKey(1), warmup=3, iters=8
    )
    toks_per_s = per_chip_batch * seq_len / mean_s
    return {
        "tokens_s_chip": round(toks_per_s, 1),
        "params_m": round(n_params / 1e6, 1),
        # Model FLOPs utilization from the 6*N*T estimate against the
        # device's bf16 peak (attention flops excluded -> conservative).
        "mfu_est": round(
            6 * n_params * toks_per_s / _device_peaks()["flops"], 4
        ),
        "per_chip_batch": per_chip_batch,
        "seq_len": seq_len,
        "step_ms_mean": round(mean_s * 1e3, 3),
        "step_ms_fenced_chunks": [round(t, 3) for t in dist],
    }


def bench_decode() -> dict:
    """KV-cache decode throughput (models.generate): batched greedy
    generation on GPT-2 124M, bf16.  tokens/s/chip counts GENERATED
    tokens across the batch; the timed region includes the prefill (one
    compiled full-prompt apply) and the lax.scan of single-token steps.
    Decode is memory-bandwidth-bound (the whole weight matrix streams
    from HBM per token), so this is the framework's HBM-bound surface
    next to the MXU-bound training numbers."""
    import jax
    import jax.numpy as jnp

    from distributeddataparallel_tpu.models import (
        TransformerLM,
        generate,
        gpt2_124m,
    )

    P, N = 128, 128
    cfg = gpt2_124m(max_seq_len=P + N, dtype=jnp.bfloat16)
    model = TransformerLM(cfg)
    rng = jax.random.PRNGKey(0)
    params = model.init(
        rng, jax.random.randint(rng, (1, P), 0, cfg.vocab_size)
    )["params"]
    n_params = sum(l.size for l in jax.tree.leaves(params))
    # generate() casts f32 masters to the compute dtype before the loop
    # (half the streamed bytes — the VERDICT r3 item 7 lever).
    weight_bytes = 2 * n_params
    # KV-cache bytes touched per decode step at position t: read the
    # whole cache so far + write one slot, per layer, per row.
    kv_per_tok = (
        2 * cfg.num_layers
        * (cfg.num_kv_heads or cfg.num_heads) * cfg.dims_per_head * 2
    )
    peak = _device_peaks()["hbm_bytes_s"]

    per_batch = {}
    # Batch sweep (VERDICT r2 weak 7): the weight stream is shared by
    # the batch, so tokens/s scales with B until the per-row KV-cache
    # stream takes over as the dominant byte budget.  B=256 shows the
    # utilization trend toward the byte roofline as per-op latency
    # amortizes.  (Two points, not three: each B costs two warm
    # executable loads through the tunnel and the driver's bench budget
    # is 560 s total.)
    for B in (8, 256):
        prompt = jax.random.randint(rng, (B, P), 0, cfg.vocab_size)
        out = generate(model, params, prompt, N)  # compile
        assert int(jnp.sum(out)) >= 0  # fence
        out1 = generate(model, params, prompt, 1)  # compile the baseline
        assert int(jnp.sum(out1)) >= 0  # fence the compile tail too
        iters = 3
        t0 = time.perf_counter()
        for _ in range(iters):
            out = generate(model, params, prompt, N)
        assert int(jnp.sum(out)) >= 0  # fence
        dt = (time.perf_counter() - t0) / iters
        # Prefill baseline: generate(.., 1) is the prompt forward + one
        # sample and none of the scanned decode steps — subtracting it
        # isolates the per-step decode cost (the B x P prefill would
        # otherwise contaminate the roofline gap, badly at B=256).
        t0 = time.perf_counter()
        for _ in range(iters):
            out1 = generate(model, params, prompt, 1)
        assert int(jnp.sum(out1)) >= 0
        dt_prefill = (time.perf_counter() - t0) / iters
        # Byte budget per decode step: weights once + the KV cache.  The
        # cache is STATIC max_seq_len-long (masked slots still stream
        # from HBM), so every step reads the full P+N window.
        cache_bytes = B * cfg.max_seq_len * kv_per_tok
        step_bytes = weight_bytes + cache_bytes
        roofline_step_ms = step_bytes / peak * 1e3
        measured_step_ms = max(dt - dt_prefill, 1e-9) / (N - 1) * 1e3
        per_batch[B] = {
            "decode_tokens_s_chip": round(B * N / dt, 1),
            "steps_per_s": round(N / dt, 1),
            # Utilization vs the FULL byte budget (weights + KV cache)
            # of the device's HBM peak: roofline step time over
            # measured.  The r03 metric counted weights only, which
            # understated b64 (cache-dominated) and ran f32 weights.
            "hbm_util_est": round(roofline_step_ms / measured_step_ms, 4),
            "roofline": {
                "weight_mb_per_step": round(weight_bytes / 1e6, 1),
                "kv_cache_mb_per_step": round(cache_bytes / 1e6, 1),
                "roofline_step_ms": round(roofline_step_ms, 4),
                "measured_step_ms": round(measured_step_ms, 4),
                "prefill_ms": round(dt_prefill * 1e3, 1),
            },
            "gen_wall_ms": round(dt * 1e3, 1),
        }
    # int8 weight-only serving (ops.quant): matrices stream as int8 +
    # per-channel scales, ~half the bf16 weight bytes.  On GPT-2 124M
    # at b8 the step is SMALL-OP-FLOOR-bound (b8_bound_analysis), so
    # the byte saving cannot show — recorded here as the honest ~1.0x;
    # the byte-bound measurement lives in int8_llama_0p6b below, where
    # the weight stream is ~10x and the dequant-fusion speedup is real
    # (measured 1.7x per step).  A HOISTED dequant would re-materialize
    # bf16 weights and erase that llama speedup — the llama number is
    # the fusion proof.
    int8 = {}
    try:
        from distributeddataparallel_tpu.ops.quant import (
            quantize_for_decode,
            quantized_bytes,
        )

        B = 8
        prompt = jax.random.randint(rng, (B, P), 0, cfg.vocab_size)
        # Quantize ONCE outside the timed loop (generate() detects the
        # QuantLeaf tree and reuses it) — timing the per-call quantize
        # pass would deflate the steady-state serving number.
        qparams = quantize_for_decode(params)
        out = generate(model, qparams, prompt, N)
        assert int(jnp.sum(out)) >= 0
        out1 = generate(model, qparams, prompt, 1)
        assert int(jnp.sum(out1)) >= 0
        iters = 3
        t0 = time.perf_counter()
        for _ in range(iters):
            out = generate(model, qparams, prompt, N)
        assert int(jnp.sum(out)) >= 0
        dt = (time.perf_counter() - t0) / iters
        t0 = time.perf_counter()
        for _ in range(iters):
            out1 = generate(model, qparams, prompt, 1)
        assert int(jnp.sum(out1)) >= 0
        dt_prefill = (time.perf_counter() - t0) / iters
        qb = quantized_bytes(qparams)["bytes"]
        cache_bytes = B * cfg.max_seq_len * kv_per_tok
        roof_ms = (qb + cache_bytes) / peak * 1e3
        meas_ms = max(dt - dt_prefill, 1e-9) / (N - 1) * 1e3
        int8 = {
            "decode_tokens_s_chip": round(B * N / dt, 1),
            # like-for-like per-step ratio (the llama section's metric):
            # end-to-end tokens/s would fold prefill into the compare
            "step_speedup_int8": round(
                per_batch[8]["roofline"]["measured_step_ms"] / meas_ms,
                3,
            ),
            "weight_mb_per_step": round(qb / 1e6, 1),
            "hbm_util_est": round(roof_ms / meas_ms, 4),
            "measured_step_ms": round(meas_ms, 4),
        }
    except Exception as e:  # noqa: BLE001 - keep the bf16 numbers
        int8 = {"error": repr(e)}

    # Byte-bound int8 proof point: Llama-0.6B-class (567M params,
    # 1.13 GB bf16 weight stream — step roofline ~1.4 ms, well above
    # the op floor).  Two variants, two timed programs each.
    int8_llama = {}
    try:
        from distributeddataparallel_tpu.models import llama3_8b

        # scan_layers: ONE compiled layer body (the production llama
        # config) — the 8-layer unrolled decode compile blew the bench
        # budget (~4 min/variant); byte totals are identical.
        lcfg = llama3_8b(
            num_layers=8, d_model=2048, d_ff=7168, num_heads=16,
            num_kv_heads=4, vocab_size=32000, max_seq_len=P + N,
            scan_layers=True, remat=False,
        )
        lmodel = TransformerLM(lcfg)
        lparams = jax.jit(lmodel.init)(
            rng, jax.random.randint(rng, (1, P), 0, lcfg.vocab_size)
        )["params"]
        B = 8
        lprompt = jax.random.randint(rng, (B, P), 0, lcfg.vocab_size)
        from distributeddataparallel_tpu.ops.quant import (
            quantize_for_decode,
        )

        lq = quantize_for_decode(lparams, scan_layers=True)
        res = {}
        for q, ps in ((None, lparams), ("int8", lq)):
            out = generate(lmodel, ps, lprompt, N)
            assert int(jnp.sum(out)) >= 0
            out1 = generate(lmodel, ps, lprompt, 1)
            assert int(jnp.sum(out1)) >= 0
            iters = 2
            t0 = time.perf_counter()
            for _ in range(iters):
                out = generate(lmodel, ps, lprompt, N)
            assert int(jnp.sum(out)) >= 0
            dt = (time.perf_counter() - t0) / iters
            t0 = time.perf_counter()
            for _ in range(iters):
                out1 = generate(lmodel, ps, lprompt, 1)
            assert int(jnp.sum(out1)) >= 0
            dtp = (time.perf_counter() - t0) / iters
            res[q or "bf16"] = {
                "decode_tokens_s_chip": round(B * N / dt, 1),
                "step_ms": round(
                    max(dt - dtp, 1e-9) / (N - 1) * 1e3, 4
                ),
            }
        int8_llama = {
            **res,
            "step_speedup_int8": round(
                res["bf16"]["step_ms"] / res["int8"]["step_ms"], 3
            ),
            "params_m": round(
                sum(x.size for x in jax.tree.leaves(lparams)) / 1e6, 1
            ),
        }
    except Exception as e:  # noqa: BLE001
        int8_llama = {"error": repr(e)}

    best = max(per_batch, key=lambda b: per_batch[b]["decode_tokens_s_chip"])
    b8 = per_batch[8]["roofline"]
    return {
        "decode_tokens_s_chip": per_batch[best]["decode_tokens_s_chip"],
        "best_batch": best,
        "hbm_util_est": per_batch[best]["hbm_util_est"],
        "hbm_util_b8": per_batch[8]["hbm_util_est"],
        "per_batch": {str(k): v for k, v in per_batch.items()},
        "int8_b8": int8,
        "int8_llama_0p6b": int8_llama,
        "prompt_len": P,
        "new_tokens": N,
        "weights_dtype": "bf16 (cast once inside the decode jit)",
        # The VERDICT r3 item 7 written roofline: at B=8 a GPT-2-124M
        # decode step's matmuls are 8-row — orders below MXU tile
        # amortization — so the step is bounded by per-op issue latency
        # across the scan body's ~25 ops/layer x 12 layers + head, not
        # by HBM bytes.  The byte roofline becomes the bound as B
        # amortizes the op overheads (see per_batch).  gap_ms is the
        # measured excess over the byte roofline; divided over ~300
        # scan-body ops it lands on the TPU's ~1-2 us small-op floor
        # (measured 0.94 us at b8).
        "b8_bound_analysis": {
            "roofline_step_ms": b8["roofline_step_ms"],
            "measured_step_ms": b8["measured_step_ms"],
            "gap_ms": round(
                b8["measured_step_ms"] - b8["roofline_step_ms"], 4
            ),
            "implied_per_op_us_at_300_ops": round(
                (b8["measured_step_ms"] - b8["roofline_step_ms"])
                / 300 * 1e3, 2,
            ),
        },
    }


def bench_moe_scaling() -> dict:
    """Token-choice MoE compute scaling (VERDICT r2 next 1's bench half):
    tokens/s as the expert count doubles at fixed top-k=2.  With
    capacity-bounded token-choice dispatch (ops.moe) the expert FLOPs
    are ~K*T regardless of E, so throughput should stay roughly flat —
    the property the dense-einsum dispatch (FLOPs ~E*T) lacks.  Single
    chip: the dispatch/capacity machinery itself; the EP all_to_all
    variant is pinned by equivalence tests and the multichip dryrun."""
    import jax
    import jax.numpy as jnp
    import numpy as np
    import optax

    import distributeddataparallel_tpu as ddp
    from distributeddataparallel_tpu.data.loader import shard_batch
    from distributeddataparallel_tpu.models import TransformerLM, gpt2_124m
    from distributeddataparallel_tpu.ops import lm_cross_entropy

    mesh = ddp.make_mesh(("data",))
    per_chip_batch, seq_len = 8, 512
    npr = np.random.default_rng(0)
    batch = shard_batch(
        {"tokens": npr.integers(
            0, 8192,
            size=(per_chip_batch * len(jax.devices()), seq_len + 1),
        ).astype(np.int32)},
        mesh,
    )

    # Build all configs first, then time in INTERLEAVED rounds taking the
    # best rate per E: the r03 artifact recorded a spurious "E=16 cliff"
    # (0.71x) that re-measurement shows was cross-section drift through
    # the driver's tunnel, not dispatch cost — sequential one-shot
    # timing is not drift-robust.  (Re-measured: E16/E4 ~ 1.05-1.13;
    # ops-level components are flat in E by construction, E*C slots and
    # expert FLOPs are E-independent at fixed top-k.)
    runs = {}
    for E in (4, 8, 16):
        cfg = gpt2_124m(
            num_layers=6, d_model=512, d_ff=2048, num_heads=8,
            vocab_size=8192, max_seq_len=seq_len, dtype=jnp.bfloat16,
            moe_experts=E, moe_top_k=2, moe_capacity_factor=1.25,
        )
        model = TransformerLM(cfg)
        params = jax.jit(model.init)(
            jax.random.PRNGKey(0), jnp.zeros((1, seq_len), jnp.int32)
        )["params"]

        def loss_fn(params, b, rng, _m=model):
            toks = b["tokens"]
            logits = _m.apply({"params": params}, toks[:, :-1])
            return lm_cross_entropy(logits, toks[:, 1:]), {}

        state = ddp.TrainState.create(
            apply_fn=model.apply, params=params, tx=optax.sgd(0.01)
        )
        state = ddp.broadcast_params(state, mesh)
        # donate=True (production config): the E-sweep is weight-traffic
        # sensitive and an undonated step adds a full param-tree copy
        # per step — linear in E, exactly the confound being measured.
        step = ddp.make_train_step(loss_fn, mesh=mesh)
        # warm (compile + first dispatches)
        for _ in range(2):
            state, _ = step(state, batch, jax.random.PRNGKey(1))
        _fence(state)
        n_params = sum(
            l.size for l in jax.tree.leaves(state.params)
        )
        runs[E] = [step, state, n_params]

    # MEDIAN of several interleaved rounds: single ~150 ms samples
    # through the tunnel carry +-30% hiccups in BOTH directions (a lucky
    # spike on one E is as misleading as a stall on another), so the
    # per-E median across interleaved rounds is the defensible
    # dispatch-cost estimate.
    samples = {E: [] for E in runs}
    for _ in range(5):
        for E, run in runs.items():
            step, state, _ = run
            t0 = time.perf_counter()
            for _ in range(8):
                state, _ = step(state, batch, jax.random.PRNGKey(1))
            run[1] = state  # donated chain: keep the live buffers
            _fence(state)
            samples[E].append(
                per_chip_batch * seq_len * 8 / (time.perf_counter() - t0)
            )
    per_e = {
        E: round(float(np.median(v)), 1) for E, v in samples.items()
    }

    # Weight-traffic roofline: at fixed tokens/chip, growing E grows the
    # f32 master weights resident per chip (dispatch slots E*C and
    # expert FLOPs stay constant at fixed top-k — the token-choice
    # property).  Each step touches ~24 B/param of experts (f32 read +
    # bf16 cast write + bf16 bwd read + f32 grad write + sgd
    # read/read/write), so the expected slowdown from E=4 to E=16 is
    # pure HBM traffic — the cost EP removes by sharding experts, not a
    # dispatch defect.  e16_over_e4_roofline is that model's prediction
    # for THIS device's bandwidth; compare with the measured ratio.
    bw = _device_peaks()["hbm_bytes_s"]
    t4 = per_chip_batch * seq_len / per_e[4]
    extra_s = (runs[16][2] - runs[4][2]) * 24 / bw
    roofline_ratio = round(t4 / (t4 + extra_s), 3)
    return {
        "tokens_s_chip_by_experts": {str(k): v for k, v in per_e.items()},
        "e16_over_e4": round(per_e[16] / per_e[4], 3),
        "e16_over_e4_weight_traffic_roofline": roofline_ratio,
        "params_m_by_experts": {
            str(E): round(r[2] / 1e6, 1) for E, r in runs.items()
        },
        "top_k": 2,
        "capacity_factor": 1.25,
        "per_chip_batch": per_chip_batch,
        "seq_len": seq_len,
        # Measured (not roofline-argued) EP weight sharding: AOT per-chip
        # memory analysis of the real EP train step, v5e 2x4 (VERDICT r4
        # weak 6).  Needs the TPU compiler; degrade loudly.
        "ep_memory": _ep_memory_evidence(),
    }


def _ep_memory_evidence() -> dict:
    from distributeddataparallel_tpu.parallel.expert_parallel import (
        ep_memory_evidence,
    )

    try:
        return ep_memory_evidence()
    except Exception as e:  # no TPU compiler reachable
        return {"error": repr(e)}


def bench_cp_ring() -> dict:
    """Ring-attention block math: Pallas-per-hop vs XLA-einsum blocks,
    fwd+bwd at training shapes (VERDICT r2 weak 6 / next 5).  One chip is
    visible, so the mesh axis has size 1 — this measures the per-hop
    BLOCK computation the ring spends its time in (the part the round-2
    README conceded was slow), not ICI transfer; multi-hop correctness
    incl. wrap masking is pinned by tests on 2/4-device rings."""
    import jax
    import jax.numpy as jnp
    import numpy as np

    import distributeddataparallel_tpu as ddp
    from jax.sharding import PartitionSpec as P
    from distributeddataparallel_tpu.parallel.context_parallel import (
        ring_attention,
    )

    mesh = ddp.make_mesh(("seq",))
    B, S, H, D = 2, 4096, 12, 64
    rng = np.random.default_rng(0)
    q = jnp.asarray(rng.standard_normal((B, S, H, D)), jnp.bfloat16)
    k = jnp.asarray(rng.standard_normal((B, S, H, D)), jnp.bfloat16)
    v = jnp.asarray(rng.standard_normal((B, S, H, D)), jnp.bfloat16)

    def timed(impl):
        def loss(q, k, v):
            o = ring_attention(q, k, v, axis_name="seq", impl=impl)
            return jnp.sum(o.astype(jnp.float32))

        f = jax.jit(jax.shard_map(
            jax.grad(loss, argnums=(0, 1, 2)), mesh=mesh,
            in_specs=(P(None, "seq"),) * 3,
            out_specs=(P(None, "seq"),) * 3, check_vma=False,
        ))
        g = f(q, k, v)
        assert float(jnp.sum(g[0].astype(jnp.float32))) == float(
            jnp.sum(g[0].astype(jnp.float32))
        )
        iters = 8
        t0 = time.perf_counter()
        for _ in range(iters):
            g = f(q, k, v)
        float(jnp.sum(g[0].astype(jnp.float32)))  # fence
        return (time.perf_counter() - t0) / iters * 1e3

    ran_pallas = jax.default_backend() == "tpu"
    xla_ms = timed("xla")
    flash_ms = timed("pallas" if ran_pallas else "xla")
    return {
        "block_fwd_bwd_ms_xla": round(xla_ms, 2),
        "block_fwd_bwd_ms_flash": round(flash_ms, 2),
        "flash_speedup": round(xla_ms / flash_ms, 2),
        "ran_pallas": ran_pallas,
        "shape": [B, S, H, D],
        "note": (
            "single visible chip: per-hop block math only; ring comms "
            "need a multi-chip axis"
        ),
    }


def bench_input_pipeline() -> dict:
    """Streaming input pipeline vs device rate (config 3's host side):
    ImageNet-geometry batches (global batch 128) streamed from a
    memmapped shard set (data.sharded) in the TPU-native split — host
    does the u8 shard gather, the device does normalize in-graph.

    Rates reported: ``host_gather_img_s`` (the pipeline's sustainable
    feed rate) and ``host_to_device_img_s`` (including placement through
    this environment's tunneled PCIe — a lower bound, the tunnel is not
    real PCIe).  The done-bar comparison host_gather >= device rate is
    computed in main() against bench_resnet50's img/s/chip.
    """
    import os
    import tempfile

    import jax
    import jax.numpy as jnp
    import numpy as np

    import distributeddataparallel_tpu as ddp
    from distributeddataparallel_tpu.data import (
        DataLoader,
        ShardedImageDataset,
        write_synthetic_image_shards,
    )

    n_examples, shape = 2048, (224, 224, 3)
    # Geometry-keyed cache dir: changing the constants regenerates, and
    # a partial/stale corpus (killed prior run) is detected and rebuilt.
    root = os.path.join(
        tempfile.gettempdir(),
        f"ddp_bench_shards_{n_examples}x{'x'.join(map(str, shape))}",
    )

    def _valid():
        try:
            import json as _json

            with open(os.path.join(root, "index.json")) as fh:
                m = _json.load(fh)
            return (
                m["num_examples"] == n_examples
                and tuple(m["shape"]) == shape
                and all(
                    os.path.exists(
                        os.path.join(root, f"shard_{s:05d}_images.npy")
                    )
                    for s in range(len(m["shard_counts"]))
                )
            )
        except Exception:  # noqa: BLE001
            return False

    if not _valid():
        import shutil

        shutil.rmtree(root, ignore_errors=True)
        write_synthetic_image_shards(
            root, n_examples, shape, 1000, shard_rows=512, seed=0
        )
    ds = ShardedImageDataset(root, device_normalize=True)
    mesh = ddp.make_mesh(("data",))
    n = mesh.shape["data"]
    per = max(128 // n, 1)
    out = {
        "corpus_mb": round(len(ds) * np.prod(ds.image_shape) / 1e6, 1),
        "global_batch": per * n,
        "image_shape": list(ds.image_shape),
    }

    # Host gather rate: one full epoch of u8 shard gathers (no device).
    loader = DataLoader(
        ds, per_replica_batch=per, mesh=mesh, seed=0, device_feed=False
    )
    next(iter(loader))  # touch pages once so timing sees steady state
    t0 = time.perf_counter()
    rows = 0
    for b in loader:
        rows += b["image"].shape[0]
    out["host_gather_img_s"] = round(rows / (time.perf_counter() - t0), 1)

    # Gather + device placement (tunneled PCIe here; capped steps).
    loader = DataLoader(
        ds, per_replica_batch=per, mesh=mesh, seed=0, device_feed=True
    )
    it = iter(loader)
    first = next(it)  # compile/placement warmup
    jax.block_until_ready(first["image"])
    t0 = time.perf_counter()
    rows = 0
    last = first
    for _ in range(6):
        try:
            last = next(it)
        except StopIteration:
            break
        rows += per * n
    # value fence: tunneled block_until_ready under-reports (see _fence)
    float(jnp.sum(last["image"].astype(jnp.int32)))
    if rows:
        out["host_to_device_img_s"] = round(
            rows / (time.perf_counter() - t0), 1
        )

    # Token host-gather rate (data.tokens vectorized sliding-window
    # gather, VERDICT r4 item 8): same >=-device-rate done-bar as images,
    # computed in main() against bench_gpt2's tokens/s/chip.
    import tempfile as _tf

    from distributeddataparallel_tpu.data import TokenFileDataset

    from distributeddataparallel_tpu.data import write_token_file

    tok_path = os.path.join(_tf.gettempdir(), "ddp_bench_tokens.npy")
    n_tok, S = 8_000_000, 1024
    if not (
        os.path.exists(tok_path)
        and np.load(tok_path, mmap_mode="r").shape == (n_tok,)
    ):
        npr = np.random.default_rng(0)
        write_token_file(
            tok_path, npr.integers(0, 50257, size=(n_tok,))
        )
    tds = TokenFileDataset(tok_path, seq_len=S)
    bsz = 64
    order = np.random.default_rng(1).permutation(len(tds))
    tds.gather(order[:bsz])  # touch pages once
    t0 = time.perf_counter()
    toks = 0
    for lo in range(0, len(order) - bsz, bsz):
        b = tds.gather(order[lo : lo + bsz])
        toks += b["tokens"].size
    out["token_gather_tok_s"] = round(toks / (time.perf_counter() - t0), 1)
    return out


def bench_pipeline_bubble() -> dict:
    """Interleaved-1F1B bubble accounting (VERDICT r4 item 5): exact
    per-device idle from the schedule's own tick arithmetic
    (``pp_bubble_fraction`` — the compiled scan length IS this T), for
    the plain vs interleaved schedules at bench-relevant geometry.
    Schedule math, not wall clock, so it is fabric-independent; the
    numerics equivalence is pinned by tests/test_pipeline_parallel.py."""
    from distributeddataparallel_tpu.parallel.pipeline_parallel import (
        pp_bubble_fraction,
    )

    out = {}
    for n, m in ((4, 16), (8, 32)):
        row = {}
        for v in (1, 2, 4):
            b = pp_bubble_fraction(n, m, v)
            row[f"v{v}"] = {
                "bubble_fraction": b["bubble_fraction"],
                "bubble_stage_units": b["bubble_stage_units"],
            }
        row["v4_over_v1_bubble"] = round(
            row["v4"]["bubble_stage_units"] / row["v1"]["bubble_stage_units"],
            3,
        )
        out[f"stages{n}_mb{m}"] = row
    return out


def _pipeline_zb_child(out_path, events_dir, env):
    """Measured-bubble comparison in a fresh 8-device CPU-mesh
    interpreter: run the REAL compiled 1f1b and zb schedules at
    (4 stages, 16 mb) and (8 stages, 32 mb), timing steady-state steps
    and — the point of the exercise — recovering the bubble from the
    schedules' own phase counters through the events pipeline: emit a
    ``pp_phase`` record per (config, schedule), then reconstruct
    ``measured_bubble_fraction`` from the merged timeline exactly the
    way ddp_report does post hoc.  The measured number comes from what
    the compiled scans executed, not from tick arithmetic."""
    import os

    os.environ.update(env)
    import json
    import time

    import jax
    import jax.numpy as jnp
    import numpy as np
    import optax

    import distributeddataparallel_tpu as ddp
    from distributeddataparallel_tpu.data.loader import shard_batch
    from distributeddataparallel_tpu.models import TransformerLM, tiny_lm
    from distributeddataparallel_tpu.observability.events import (
        EventLog,
        events_path,
        load_timeline,
    )
    from distributeddataparallel_tpu.observability.pipeline import (
        measured_bubble_fraction,
        phase_counts_payload,
    )
    from distributeddataparallel_tpu.parallel.pipeline_parallel import (
        make_pp_train_step,
        shard_state_pp,
    )

    out = {}
    for stages, M in ((4, 16), (8, 32)):
        # 8 layers: divisible by both stage counts; local batch shard =
        # M rows (one row per microbatch) so the M-way reshape is exact.
        cfg = tiny_lm(
            num_layers=8, num_heads=2, d_model=32, d_ff=64,
            scan_layers=True, max_seq_len=32,
        )
        n_data = 8 // stages
        mesh = ddp.make_mesh(("data", "pipe"), shape=(n_data, stages))
        params = TransformerLM(cfg).init(
            jax.random.PRNGKey(0), jnp.zeros((1, 32), jnp.int32)
        )["params"]
        tokens = np.random.default_rng(stages).integers(
            0, 256, size=(M * n_data, 33)
        ).astype(np.int32)
        batch = shard_batch({"tokens": tokens}, mesh)
        row = {}
        for schedule in ("1f1b", "zb"):
            step = make_pp_train_step(
                cfg, mesh=mesh, microbatches=M, donate=False,
                schedule=schedule,
            )
            state = shard_state_pp(
                ddp.TrainState.create(
                    apply_fn=None, params=params, tx=optax.sgd(0.1)
                ),
                mesh,
            )
            state, metrics = step(state, batch, jax.random.PRNGKey(0))
            jax.block_until_ready(metrics["loss"])  # compile + warm
            times = []
            for it in range(1, 4):
                t0 = time.perf_counter()
                state, metrics = step(state, batch, jax.random.PRNGKey(it))
                jax.block_until_ready(metrics["loss"])
                times.append(time.perf_counter() - t0)

            # One events dir per (config, schedule): the bench IS a
            # miniature run, reconstructed the same way a real run is.
            edir = os.path.join(
                events_dir, f"stages{stages}_{schedule}"
            )
            with EventLog(events_path(edir, 0), proc=0) as log:
                log.emit("pp_phase", **phase_counts_payload(
                    jax.device_get(metrics["pp_phase_counts"]),
                    schedule=schedule, n_stages=stages, virtual=1,
                    microbatches=M,
                    accounting=step.bubble_accounting,
                ))
            measured = measured_bubble_fraction(load_timeline(edir))
            row[schedule] = {
                "step_s": round(sorted(times)[len(times) // 2], 4),
                "measured_bubble_fraction": (
                    measured or {}
                ).get("measured_bubble_fraction"),
                "analytic_bubble_fraction": (
                    measured or {}
                ).get("analytic_bubble_fraction"),
                "per_stage_useful": [
                    s["useful_slots"] for s in (measured or {}).get(
                        "per_stage", []
                    )
                ],
            }
        zb, fb = row["zb"], row["1f1b"]
        if None not in (
            zb["measured_bubble_fraction"], fb["measured_bubble_fraction"]
        ):
            row["zb_vs_1f1b_measured"] = round(
                zb["measured_bubble_fraction"]
                / max(fb["measured_bubble_fraction"], 1e-9), 3,
            )
        out[f"stages{stages}_mb{M}"] = row
    with open(out_path, "w") as fh:
        json.dump(out, fh)


def bench_pipeline_zb() -> dict:
    """Zero-bubble pipeline done bar: measured zb bubble (from the
    compiled schedules' phase counters, reconstructed through the
    events timeline) below the ANALYTIC 1F1B fraction at the same
    (stages, microbatches) — both the v1 geometry it replaces and the
    interleave-v4 roofline the 1F1B study recorded.  The analytic
    table from ``bench_pipeline_bubble`` rides along as the roofline
    column; headline keys ``zb_bubble_frac`` / ``zb_step_s`` are gated
    lower-is-better by perf_gate."""
    import json as _json
    import multiprocessing as mp
    import os
    import tempfile

    out = {"analytic": bench_pipeline_bubble()}
    root = tempfile.mkdtemp(prefix="ddp_bench_zb_")
    out_path = os.path.join(root, "out.json")
    env = {
        "JAX_PLATFORMS": "cpu",
        "XLA_FLAGS": "--xla_force_host_platform_device_count=8",
    }
    ctx = mp.get_context("spawn")
    p = ctx.Process(
        target=_pipeline_zb_child,
        args=(out_path, os.path.join(root, "events"), env),
    )
    p.start()
    p.join(timeout=600)
    if p.is_alive():
        p.terminate()
        p.join()
        out["error"] = "child timed out"
        return out
    if p.exitcode != 0 or not os.path.exists(out_path):
        out["error"] = f"child exit {p.exitcode}"
        return out
    with open(out_path) as fh:
        out["measured"] = _json.load(fh)

    beats = []
    for key in ("stages4_mb16", "stages8_mb32"):
        row = out["measured"].get(key, {})
        zb = row.get("zb", {}).get("measured_bubble_fraction")
        roof = out["analytic"].get(key, {})
        row["analytic_1f1b_v1_bubble"] = (
            roof.get("v1", {}).get("bubble_fraction")
        )
        row["analytic_1f1b_v4_bubble"] = (
            roof.get("v4", {}).get("bubble_fraction")
        )
        if zb is not None and row["analytic_1f1b_v1_bubble"] is not None:
            row["zb_beats_1f1b_analytic"] = bool(
                zb < row["analytic_1f1b_v1_bubble"]
                and zb < row["analytic_1f1b_v4_bubble"]
            )
            beats.append(row["zb_beats_1f1b_analytic"])
    zb_fracs = [
        out["measured"][k]["zb"]["measured_bubble_fraction"]
        for k in ("stages4_mb16", "stages8_mb32")
        if out["measured"].get(k, {}).get("zb", {}).get(
            "measured_bubble_fraction"
        ) is not None
    ]
    if zb_fracs:
        # worst (largest) measured bubble across configs — conservative
        out["zb_bubble_frac"] = max(zb_fracs)
    step_s = out["measured"].get("stages8_mb32", {}).get("zb", {}).get(
        "step_s"
    )
    if step_s is not None:
        out["zb_step_s"] = step_s
    out["zb_beats_1f1b_analytic"] = bool(beats) and all(beats)
    return out


def bench_overlap() -> dict:
    """Comm/compute overlap on the GPT-2 124M DP step (BASELINE config 5's
    "overlap demonstrated"): full step vs compute-only (grad_sync=False,
    the no_sync analog) vs bare grad-tree all-reduce.  With one visible
    chip the collective is a no-op (overlap_frac None); on a multi-chip
    axis the fraction quantifies how much of the psum XLA hides under the
    backward."""
    import jax
    import optax

    from distributeddataparallel_tpu.utils.metrics import overlap_probe

    mesh, loss_fn, state, batch = _gpt2_setup("auto", tx=optax.sgd(0.01))
    out = overlap_probe(
        loss_fn, state, batch, jax.random.PRNGKey(1), mesh=mesh, iters=4
    )

    # The scheduled-HLO demonstration (OVERLAP.md): AOT-compile the REAL
    # train steps — GPT-2 124M (unrolled, adamw) and the Llama-0.6B
    # scan+remat config with the in-scan-body reduction — for an 8-chip
    # v5e topology and report how much backward compute the TPU compiler
    # scheduled inside the async-collective windows (VERDICT r4 item 1:
    # rounds 1-4 recorded an 8-layer-MLP proxy here).  The MLP pair
    # (chain-vs-stock contrast) still lands in MULTICHIP_PROBES.json
    # every dryrun.
    keys = (
        "n_async_windows", "n_sync_collectives", "n_comm_fused",
        "overlapped_compute_cycles", "total_compute_cycles",
        "overlapped_frac_of_compute", "async_collective_bytes",
        "sync_collective_bytes", "async_bytes_frac", "topology",
        "n_chips", "compiler", "compile_s", "config", "while_bodies",
    )
    from distributeddataparallel_tpu.parallel.overlap import (
        train_step_schedule_evidence,
    )

    for m in ("gpt2", "llama"):
        try:
            rep = train_step_schedule_evidence(model=m)
            out[f"real_step_schedule_{m}"] = {k: rep[k] for k in keys}
        except Exception as e:  # noqa: BLE001 - keep the other sections
            out[f"real_step_schedule_{m}"] = {"error": repr(e)}

    # Comm-hook wire-byte ledgers for the GPT-2 124M gradient tree
    # (shape math, no compile): the bf16 hook halves the wire; the
    # PowerSGD hook's rank-4 factors cut it by orders of magnitude.
    # Schedule-level measurements for bf16 are in OVERLAP.md §6.
    from distributeddataparallel_tpu.parallel.powersgd import (
        powersgd_wire_bytes,
    )

    try:
        out["comm_hooks_wire_bytes"] = {
            "powersgd_rank4": powersgd_wire_bytes(state.params, rank=4),
            "bf16_wire_bytes": sum(
                2 * l.size for l in jax.tree.leaves(state.params)
            ),
        }
    except Exception as e:  # noqa: BLE001
        out["comm_hooks_wire_bytes"] = {"error": repr(e)}
    return out


def _warm_start_child(mode, cache_dir, store_dir, out_path, env):
    """One warm-start measurement, run in a FRESH interpreter (spawn):
    compile/cache/AOT state is per-process, so only a new process can
    observe a cold start or a genuine restart.  Always an 8-device
    virtual CPU mesh (env pins JAX_PLATFORMS + host device count before
    jax imports) — the measurement is host-side executable acquisition,
    which must not tie up the shared TPU tunnel."""
    import os

    os.environ.update(env)
    import json
    import time

    t_start = time.perf_counter()
    import jax
    import jax.numpy as jnp
    import numpy as np
    import optax

    import distributeddataparallel_tpu as ddp
    from distributeddataparallel_tpu.data.loader import shard_batch
    from distributeddataparallel_tpu.models import TransformerLM, gpt2_124m
    from distributeddataparallel_tpu.ops import lm_cross_entropy
    from distributeddataparallel_tpu.training.warm_start import (
        ExecutableStore,
        enable_compile_cache,
        executable_key,
        warm_train_step,
    )

    enable_compile_cache(cache_dir)
    mesh = ddp.make_mesh(("data",))
    # GPT-2 124M with scanned layers at short seq: full-width weight
    # tree (the compile cost that matters) at a CPU-affordable step.
    seq_len = 64
    cfg = gpt2_124m(max_seq_len=seq_len, scan_layers=True)
    model = TransformerLM(cfg)
    shapes = jax.eval_shape(
        model.init, jax.random.PRNGKey(0), jnp.zeros((1, seq_len), jnp.int32)
    )
    # Zero params via eval_shape: real init costs more than the step on
    # CPU and the timing target is the executable path, not the values.
    params = jax.tree.map(
        lambda s: jnp.zeros(s.shape, s.dtype), shapes
    )["params"]

    def loss_fn(params, batch, rng):
        toks = batch["tokens"]
        logits = model.apply({"params": params}, toks[:, :-1])
        return lm_cross_entropy(logits, toks[:, 1:]), {}

    state = ddp.TrainState.create(
        apply_fn=model.apply, params=params,
        tx=optax.sgd(0.01, momentum=0.9),
    )
    state = ddp.broadcast_params(state, mesh)
    step_fn = ddp.make_train_step(loss_fn, mesh=mesh, donate=False)
    warm = warm_train_step(
        step_fn,
        store=ExecutableStore(store_dir),
        key=executable_key(
            mesh=mesh, model_config=cfg,
            step_signature=getattr(step_fn, "aot_signature", None),
            extra={"bench": "warm_start", "seq_len": seq_len},
        ),
    )
    npr = np.random.default_rng(0)
    B = 2 * len(jax.devices())
    batch = shard_batch(
        {"tokens": npr.integers(
            0, 50257, size=(B, seq_len + 1)
        ).astype(np.int32)},
        mesh,
    )
    # Time ACQUISITION only (resolve, not a step): on the 8-thread
    # virtual CPU mesh one GPT-2 step takes ~60 s of execution, which
    # would drown the compile-vs-load contrast being measured.  The
    # loaded binary's bitwise equivalence to the cold compile is pinned
    # by tests/test_warm_start.py on the same backend.
    rep = warm.resolve(state, batch, jax.random.PRNGKey(0))
    rep["acquire_s"] = rep.get("load_s", rep.get("compile_s"))
    rep.update(
        requested=mode,
        start_to_ready_s=round(time.perf_counter() - t_start, 3),
    )
    with open(out_path, "w") as fh:
        json.dump(rep, fh)


def _restart_latency_worker(process_id, cache_dir, store_dir, out_dir):
    """Supervised-gang worker for the restart-latency measurement: the
    first incarnation compiles, saves the executable, then dies like a
    preemption; the respawn (DDP_RESTART_ATTEMPT=1) should reach its
    first step via the AOT store.  env is already applied by the
    launcher's child bootstrap."""
    import os

    attempt = int(os.environ.get("DDP_RESTART_ATTEMPT", "0"))
    _warm_start_child(
        f"attempt{attempt}", cache_dir, store_dir,
        os.path.join(out_dir, f"attempt{attempt}.json"), {},
    )
    if attempt == 0:
        raise SystemExit(1)


def bench_warm_start() -> dict:
    """Warm-start subsystem (training.warm_start): first-step latency of
    the SAME GPT-2 124M train step acquired three ways — cold compile,
    persistent-cache hit, and AOT executable load — each in a fresh
    process on an 8-device virtual CPU mesh.  The done bar: cache-hit or
    AOT-load at least 5x faster to the first step than the cold compile.
    With DDP_BENCH_SLOW set, also measures restart-to-first-step latency
    under the PR 1 supervisor (spawn max_restarts=1): incarnation 0
    compiles + saves + dies, incarnation 1 must come back via AOT."""
    import json as _json
    import multiprocessing as mp
    import os
    import tempfile

    root = tempfile.mkdtemp(prefix="ddp_bench_warm_")
    cache_dir = os.path.join(root, "cache")
    store_a = os.path.join(root, "aot_a")
    store_b = os.path.join(root, "aot_b")  # stays empty: forces compile
    env = {
        "JAX_PLATFORMS": "cpu",
        "XLA_FLAGS": "--xla_force_host_platform_device_count=8",
    }
    ctx = mp.get_context("spawn")
    out = {}
    runs = (
        ("cold", store_a),       # fresh cache + store: full compile + save
        ("cache_hit", store_b),  # warm cache, empty store: cached compile
        ("aot", store_a),        # populated store: deserialize, no trace
    )
    for mode, store in runs:
        out_path = os.path.join(root, f"{mode}.json")
        p = ctx.Process(
            target=_warm_start_child,
            args=(mode, cache_dir, store, out_path, env),
        )
        p.start()
        p.join(timeout=420)
        if p.is_alive():
            p.terminate()
            p.join()
            out[mode] = {"error": "child timed out"}
        elif p.exitcode != 0 or not os.path.exists(out_path):
            out[mode] = {"error": f"child exit {p.exitcode}"}
        else:
            with open(out_path) as fh:
                out[mode] = _json.load(fh)
    try:
        cold_s = out["cold"]["acquire_s"]
        out["cache_hit_speedup"] = round(
            cold_s / out["cache_hit"]["acquire_s"], 2
        )
        out["aot_speedup"] = round(cold_s / out["aot"]["acquire_s"], 2)
        out["modes"] = [out[m]["mode"] for m, _ in runs]
    except (KeyError, TypeError, ZeroDivisionError):
        pass  # a child failed; its error record is already in out

    if os.environ.get("DDP_BENCH_SLOW"):
        from distributeddataparallel_tpu.runtime.launcher import spawn

        r_root = os.path.join(root, "restart")
        os.makedirs(r_root, exist_ok=True)
        try:
            spawn(
                _restart_latency_worker,
                args=(
                    os.path.join(r_root, "cache"),
                    os.path.join(r_root, "aot"),
                    r_root,
                ),
                nprocs=1, max_restarts=1, restart_backoff_s=0.1, env=env,
            )
            att = {}
            for a in (0, 1):
                with open(
                    os.path.join(r_root, f"attempt{a}.json")
                ) as fh:
                    att[a] = _json.load(fh)
            out["restart_latency"] = {
                f"attempt{a}": {
                    k: att[a][k] for k in (
                        "mode", "acquire_s", "start_to_ready_s"
                    )
                }
                for a in (0, 1)
            }
            out["restart_latency"]["restart_speedup"] = round(
                att[0]["start_to_ready_s"] / att[1]["start_to_ready_s"], 2
            )
        except Exception as e:  # noqa: BLE001 — keep the fast numbers
            out["restart_latency"] = {"error": repr(e)}
    else:
        out["restart_latency"] = {"skipped": "set DDP_BENCH_SLOW=1"}
    return out


def bench_elastic_resize() -> dict:
    """Elastic gang resize vs supervised cold restart, head to head: the
    SAME 8-fake-device CPU gang loses one worker mid-run (chaos), once
    with ``--elastic`` (in-process resize to 7, no checkpoint read) and
    once under the fixed-size supervisor (whole-gang respawn, AOT warm
    start — the strongest restart baseline this repo has).  Downtime is
    measured the same way on both sides, from the timeline each run
    leaves behind: first post-recovery step-span ts minus the
    chaos_inject ts.  Headlines: ``resize_downtime_s`` (lower-better)
    and ``restart_reclaimed_s`` = cold restart minus resize downtime
    (ends in _s but HIGHER is better — seconds given back; perf_gate's
    _HIGHER_BETTER knows the suffix)."""
    import os
    import subprocess
    import sys
    import tempfile

    from distributeddataparallel_tpu.observability.events import (
        load_timeline,
    )

    here = os.path.dirname(os.path.abspath(__file__))
    root = tempfile.mkdtemp(prefix="ddp_bench_elastic_")
    env = dict(
        os.environ,
        JAX_PLATFORMS="cpu",
        XLA_FLAGS="--xla_force_host_platform_device_count=8",
    )
    env.pop("_DDP_SUPERVISED", None)
    env.pop("DDP_ELASTIC_WORLD", None)
    base = [
        sys.executable, os.path.join(here, "dpp.py"),
        "--model", "mlp", "--fake-devices", "8", "--batch-size", "4",
        "--epochs", "1", "--steps-per-epoch", "12",
    ]
    runs = {
        # in-process resize: kill rank 5 at step 4, keep training at 7
        "resize": ["--elastic", "--chaos", "worker-kill@4:5"],
        # fixed-size baseline: same loss at the same step, whole-gang
        # respawn through the supervisor (checkpoint-dir is required by
        # --max-restarts and hosts the chaos marker files that keep the
        # preempt from re-firing in the respawn)
        "restart": ["--chaos", "preempt@4", "--max-restarts", "1"],
    }
    out = {}
    records = {}
    for mode, extra in runs.items():
        ev = os.path.join(root, f"ev_{mode}")
        cc = os.path.join(root, f"cc_{mode}")
        cmd = base + extra + ["--events-dir", ev, "--compile-cache", cc]
        if mode == "restart":
            cmd += ["--checkpoint-dir", os.path.join(root, "ckpt")]
        try:
            proc = subprocess.run(
                cmd, env=env, cwd=here, timeout=420,
                capture_output=True, text=True,
            )
        except subprocess.TimeoutExpired:
            out[mode] = {"error": "timed out"}
            continue
        recs = load_timeline(ev) if os.path.isdir(ev) else []
        records[mode] = recs
        out[mode] = {
            "exit": proc.returncode,
            "n_records": len(recs),
            "kinds": sorted({r.get("kind") for r in recs
                             if r.get("kind") in (
                                 "gang_resize", "restart_attempt",
                                 "resize_downtime")}),
        }
        if proc.returncode != 0:
            out[mode]["error"] = (proc.stderr or "")[-400:]

    def downtime(recs, disrupt_prefix, recover_kind):
        """First step-span ts at or after the recovery marker, minus the
        chaos_inject ts — the wall seconds training stood still."""
        dis = next((r["ts"] for r in recs
                    if r.get("kind") == "chaos_inject"
                    and str(r.get("entry", "")).startswith(disrupt_prefix)),
                   None)
        mark = next((r["ts"] for r in recs
                     if r.get("kind") == recover_kind), None)
        if dis is None or mark is None:
            return None
        rec = min((r["ts"] for r in recs
                   if r.get("kind") == "span" and r.get("name") == "step"
                   and r["ts"] >= mark), default=None)
        return None if rec is None else round(rec - dis, 3)

    rd = downtime(records.get("resize", []), "worker-kill", "gang_resize")
    cd = downtime(records.get("restart", []), "preempt", "restart_attempt")
    out["resize_downtime_s"] = rd
    out["cold_restart_s"] = cd
    if rd is not None and cd is not None:
        out["restart_reclaimed_s"] = round(cd - rd, 3)
        out["resize_beats_restart"] = rd < cd
    # the done bar of the elastic subsystem: the resize path must never
    # have fallen back to supervision, and vice versa
    out["resize_clean"] = (
        "restart_attempt" not in out.get("resize", {}).get("kinds", ())
        and "gang_resize" in out.get("resize", {}).get("kinds", ())
    )
    return out


def _observability_child(out_path, events_dir, env):
    """Telemetry-overhead measurement in a fresh 8-device CPU-mesh
    interpreter (same isolation rationale as _warm_start_child: the
    measurement must not tie up the shared TPU tunnel, and the CPU mesh
    is the acceptance target).  Three answers into out_path:

    - step_s_off / step_s_on: the SAME compiled GPT-2 124M step timed
      with observability disabled, then wired exactly as dpp.py wires it
      (per-step span, profiler hooks, steps_total counter,
      --metrics-every export cadence, the PR 5 attribution layer: MFU
      meter + memory sampling at the window boundary, and the alert
      engine evaluated at that same boundary);
    - syncs_off / syncs_on: jax.block_until_ready call counts in each
      loop — the telemetry-on loop must add ZERO;
    - telemetry_us_per_step: the per-step telemetry work microbenchmarked
      alone (2000 reps), the high-resolution form of the same overhead —
      differencing two multi-second step loops cannot resolve a
      sub-millisecond cost, the micro number can.
    """
    import os

    os.environ.update(env)
    import json
    import time

    import jax

    import bench as _bench
    from distributeddataparallel_tpu.observability import (
        AlertEngine,
        EventLog,
        JsonlExporter,
        MemoryTelemetry,
        MetricsRegistry,
        MFUMeter,
        ProfilerOrchestrator,
        Tracer,
        events_path,
        train_step_flops,
        transformer_fwd_flops,
        validate_file,
    )
    from distributeddataparallel_tpu.training.train_step import (
        make_train_step,
    )

    mesh, loss_fn, state, batch = _bench._gpt2_setup(
        "xla", per_chip_batch=2, seq_len=64
    )
    step = make_train_step(loss_fn, mesh=mesh, donate=False)
    key = jax.random.PRNGKey(0)

    # Count EVERY host sync either loop performs.
    real_block = jax.block_until_ready
    syncs = {"n": 0}

    def counting_block(x):
        syncs["n"] += 1
        return real_block(x)

    jax.block_until_ready = counting_block
    try:
        real_block(step(state, batch, key)[0].params)  # compile + warm
        # 2 iterations suffice: the loop exists to COUNT syncs (exact at
        # any length) and sanity-check the wall clock; the resolution
        # question is answered by the micro-benchmark below.  On a
        # 1-core host the 8-device virtual mesh runs one GPT-2 step in
        # ~1 min, so the loop length is the child's time budget.
        ITERS = 2

        def loop(tracer=None, prof=None, registry=None, metrics_every=100,
                 steps_total=None, mfu_meter=None, mem_tel=None,
                 alert_engine=None):
            syncs["n"] = 0
            s = state
            t0 = time.perf_counter()
            for i in range(ITERS):
                if prof is not None:
                    prof.on_step_start(i)
                if tracer is not None:
                    with tracer.span("step", step=i):
                        s, _ = step(s, batch, key)
                else:
                    s, _ = step(s, batch, key)
                if prof is not None:
                    prof.on_step_end(i)
                if steps_total is not None:
                    steps_total.inc()
                if registry is not None and i % metrics_every == 0:
                    registry.export(step=i)
            jax.block_until_ready(s.params)  # the one boundary drain
            dt = (time.perf_counter() - t0) / ITERS
            # The PR 5 attribution work runs exactly where dpp.py runs
            # it: AT the boundary where the loop already drained.  Kept
            # inside the counted region so syncs_on would expose any
            # device round-trip the meters sneaked in.
            att = sample = None
            if mfu_meter is not None:
                att = mfu_meter.on_reading(
                    {"steps_per_s": 1.0 / dt}, step=ITERS
                )
            if mem_tel is not None:
                sample = mem_tel.sample(ITERS)
            if alert_engine is not None:
                # Same contract as dpp.py: the engine sees only host
                # floats this boundary already computed, inside the
                # counted region so any device read it sneaked in would
                # show up in syncs_on.
                alert_engine.observe(
                    step=ITERS,
                    step_s=dt,
                    mfu=att["mfu"] if att else None,
                    live_hwm_bytes=(
                        sample.get("live_hwm_bytes") if sample else None
                    ),
                    restarts=0,
                )
            return dt, syncs["n"]

        step_s_off, syncs_off = loop()

        events = EventLog(events_path(events_dir, 0), 0)
        events.emit("run_start", argv=["bench_observability"])
        registry = MetricsRegistry()
        registry.add_exporter(JsonlExporter(events))
        registry.bind("faults", lambda: {"nonfinite_steps": 0})
        tracer = Tracer(events, registry)
        prof = ProfilerOrchestrator(None, events=events)  # disabled dir
        steps_total = registry.counter("steps_total")
        # Same cost model dpp.py --mfu builds: the fixture IS gpt2_124m
        # at per-chip batch 2, seq 64 (loss applies tokens[:, :-1]).
        from distributeddataparallel_tpu.models import gpt2_124m

        cfg = gpt2_124m(max_seq_len=64)
        fwd = transformer_fwd_flops(
            cfg, batch=2 * len(jax.devices()), seq_len=63
        )
        mfu_meter = MFUMeter(
            train_step_flops(fwd, remat=getattr(cfg, "remat", False)),
            n_chips=len(jax.devices()),
            peak_flops_per_chip=None,  # virtual CPU mesh: FLOP/s only
            registry=registry,
            events=events,
        )
        mem_tel = MemoryTelemetry(registry, events, jax.local_devices())
        alert_engine = AlertEngine(events=events, registry=registry)
        step_s_on, syncs_on = loop(
            tracer, prof, registry,
            steps_total=steps_total, mfu_meter=mfu_meter, mem_tel=mem_tel,
            alert_engine=alert_engine,
        )
        events.emit("run_end", status="ok")

        # Micro: the per-step telemetry work alone, at default cadence —
        # including the PR 5 boundary work (MFU arithmetic + live-array
        # walk) and the alert-rule evaluation at a window-ish cadence
        # of 100.
        REPS = 2000
        t0 = time.perf_counter()
        for i in range(REPS):
            prof.on_step_start(i)
            with tracer.span("step", step=i):
                pass
            prof.on_step_end(i)
            steps_total.inc()
            if i % 100 == 0:
                registry.export(step=i)
                att = mfu_meter.on_reading({"steps_per_s": 1.0}, step=i)
                sample = mem_tel.sample(i)
                alert_engine.observe(
                    step=i, step_s=1.0, mfu=att["mfu"],
                    live_hwm_bytes=(
                        sample.get("live_hwm_bytes") if sample else None
                    ),
                    restarts=0,
                )
        telemetry_us = (time.perf_counter() - t0) / REPS * 1e6
        events.close()
    finally:
        jax.block_until_ready = real_block

    problems = validate_file(events_path(events_dir, 0))
    with open(out_path, "w") as fh:
        json.dump({
            "step_s_off": round(step_s_off, 4),
            "step_s_on": round(step_s_on, 4),
            "overhead_frac_loop": round(step_s_on / step_s_off - 1.0, 4),
            "syncs_off": syncs_off,
            "syncs_on": syncs_on,
            "telemetry_us_per_step": round(telemetry_us, 1),
            "overhead_frac_micro": round(
                telemetry_us / 1e6 / step_s_off, 6
            ),
            "events_valid": not problems,
            "events_problems": problems[:5],
        }, fh)


def bench_observability() -> dict:
    """Observability done bar (PR 3 harness, extended with the PR 5
    attribution layer): with --events-dir, the steps_total counter, the
    MFU meter and memory sampling all wired at default cadence, step
    throughput on the 8-device CPU mesh (GPT-2 124M) stays within 2% of
    telemetry-off, with zero extra host syncs and a schema-valid event
    file."""
    import json as _json
    import multiprocessing as mp
    import os
    import tempfile

    root = tempfile.mkdtemp(prefix="ddp_bench_obs_")
    out_path = os.path.join(root, "out.json")
    env = {
        "JAX_PLATFORMS": "cpu",
        "XLA_FLAGS": "--xla_force_host_platform_device_count=8",
    }
    ctx = mp.get_context("spawn")
    p = ctx.Process(
        target=_observability_child,
        args=(out_path, os.path.join(root, "events"), env),
    )
    p.start()
    # Unlike the warm-start children (compile only), this child runs
    # the compiled step 2×ITERS+1 times; on a 1-core host that is
    # minutes, not seconds.
    p.join(timeout=900)
    if p.is_alive():
        p.terminate()
        p.join()
        return {"error": "child timed out"}
    if p.exitcode != 0 or not os.path.exists(out_path):
        return {"error": f"child exit {p.exitcode}"}
    with open(out_path) as fh:
        out = _json.load(fh)
    out["zero_extra_syncs"] = out.get("syncs_on") == out.get("syncs_off")
    out["within_2pct"] = (
        out.get("overhead_frac_micro", 1.0) < 0.02
        and out["zero_extra_syncs"]
    )
    return out


def _zero_sharding_child(out_path, env):
    """ZeRO-2/3 memory-delta measurement in a fresh 8-device CPU-mesh
    interpreter (the acceptance target of the sharded-update work is the
    8-device CPU mesh, and the live-array walk must not see another
    section's leftovers).  For dp / zero2 / zero3 on the SAME GPT-2 124M
    fixture it records, into out_path:

    - perdevice_hwm_bytes: busiest-device live-array high-water mark
      across warm steps (``live_array_bytes_per_device`` — the only view
      that can see the sharding win; global nbytes cannot);
    - step_s: mean warm step time (zero2/3 must stay within 10% of dp);
    - exec memory_analysis of the compiled step (the compiler's own
      per-device budget, the mesh-sim counterpart of the measured HWM).

    Each variant rebuilds params from the same seed and drops every
    handle before sampling, so a replicated tree from one variant can
    never inflate the next one's HWM.
    """
    import gc
    import os

    os.environ.update(env)
    import json
    import time

    import jax
    import jax.numpy as jnp
    import numpy as np
    import optax

    import distributeddataparallel_tpu as ddp
    from distributeddataparallel_tpu.data.loader import shard_batch
    from distributeddataparallel_tpu.models import TransformerLM
    from distributeddataparallel_tpu.models.transformer import gpt2_124m
    from distributeddataparallel_tpu.observability.memory import (
        MemoryTelemetry,
        executable_memory_analysis,
    )
    from distributeddataparallel_tpu.ops import lm_cross_entropy
    from distributeddataparallel_tpu.parallel.zero import zero_state
    from distributeddataparallel_tpu.training.train_step import (
        make_train_step,
    )

    SEQ, PER_CHIP, STEPS = 128, 1, 3
    mesh = ddp.make_mesh(("data",))
    n = len(jax.devices())
    cfg = gpt2_124m(max_seq_len=SEQ, scan_layers=True)
    model = TransformerLM(cfg)
    init = jax.jit(model.init)

    def loss_fn(p, batch, rng):
        toks = batch["tokens"]
        logits = model.apply({"params": p}, toks[:, :-1],
                             deterministic=True)
        return lm_cross_entropy(logits, toks[:, 1:]), {}

    npr = np.random.default_rng(0)
    batch = shard_batch(
        {"tokens": npr.integers(
            0, cfg.vocab_size, size=(PER_CHIP * n, SEQ + 1)
        ).astype(np.int32)},
        mesh,
    )
    key = jax.random.PRNGKey(0)

    results = {}
    for name, level in (("dp", 0), ("zero2", 2), ("zero3", 3)):
        params = init(
            jax.random.PRNGKey(0), jnp.zeros((1, SEQ), jnp.int32)
        )["params"]
        tx = optax.adamw(3e-4)
        if level:
            s = zero_state(apply_fn=model.apply, params=params, tx=tx,
                           mesh=mesh, level=level)
        else:
            s = ddp.broadcast_params(
                ddp.TrainState.create(
                    apply_fn=model.apply, params=params, tx=tx
                ),
                mesh,
            )
        # the unsharded init tree must die before sampling or it bills
        # ~500 MB to one device under every variant alike
        del params
        gc.collect()

        step = make_train_step(loss_fn, mesh=mesh, zero=level or False)
        compiled = step.lower(s, batch, key).compile()
        mem_tel = MemoryTelemetry()
        s, _ = step(s, batch, key)  # warm (donates the init state)
        jax.block_until_ready(jax.tree.leaves(s.params)[0])
        t0 = time.perf_counter()
        for i in range(STEPS):
            s, _ = step(s, batch, key)
            jax.block_until_ready(jax.tree.leaves(s.params)[0])
            mem_tel.sample(i)
        dt = (time.perf_counter() - t0) / STEPS
        results[name] = {
            "step_s": round(dt, 4),
            "perdevice_hwm_bytes": mem_tel.live_perdevice_hwm_bytes,
            "exec_memory": executable_memory_analysis(compiled),
        }
        del s, step, compiled
        gc.collect()

    with open(out_path, "w") as fh:
        json.dump(results, fh)


def bench_zero_sharding() -> dict:
    """Sharded weight update done bar: on the 8-device CPU mesh,
    GPT-2 124M per-device live-array HWM drops >=25% at zero2 vs dp
    (further at zero3) while step time stays within 10% of dp."""
    import json as _json
    import multiprocessing as mp
    import os
    import tempfile

    root = tempfile.mkdtemp(prefix="ddp_bench_zero_")
    out_path = os.path.join(root, "out.json")
    env = {
        "JAX_PLATFORMS": "cpu",
        "XLA_FLAGS": "--xla_force_host_platform_device_count=8",
    }
    ctx = mp.get_context("spawn")
    p = ctx.Process(target=_zero_sharding_child, args=(out_path, env))
    p.start()
    # three variants x (compile + 4 steps) of GPT-2 on a virtual
    # 8-device mesh: minutes on a 1-core host, like bench_observability
    p.join(timeout=900)
    if p.is_alive():
        p.terminate()
        p.join()
        return {"error": "child timed out"}
    if p.exitcode != 0 or not os.path.exists(out_path):
        return {"error": f"child exit {p.exitcode}"}
    with open(out_path) as fh:
        out = _json.load(fh)
    dp_hwm = out.get("dp", {}).get("perdevice_hwm_bytes") or 0
    dp_s = out.get("dp", {}).get("step_s") or 0.0
    for v in ("zero2", "zero3"):
        rec = out.get(v)
        if not rec or not dp_hwm:
            continue
        rec["hwm_drop_vs_dp"] = round(
            1.0 - rec["perdevice_hwm_bytes"] / dp_hwm, 4
        )
        if dp_s:
            rec["step_over_dp"] = round(rec["step_s"] / dp_s, 3)
    out["meets_25pct_drop"] = bool(
        out.get("zero2", {}).get("hwm_drop_vs_dp", 0.0) >= 0.25
    )
    return out


def _autotune_child(out_path, env):
    """Autotuner acceptance run in a fresh 8-device CPU-mesh
    interpreter: a small but real search over GPT-2 124M (short seq)
    with the hand-picked default as the measured baseline.  Writes the
    winner, the baseline, and the gain to out_path.

    The baseline is what a careful human would type on this box —
    per-chip batch 1 with remat on — so ``gain_frac`` is the honest
    answer to "did the tuner beat me", not a strawman.
    """
    import os

    os.environ.update(env)
    import json
    import tempfile

    import distributeddataparallel_tpu as ddp
    from distributeddataparallel_tpu.tuning import (
        SearchSpace,
        TrialConfig,
        TuningStore,
        search_model,
    )

    mesh = ddp.make_mesh(("data",))
    space = SearchSpace(
        batch_per_chip=(1, 2), accum_steps=(1,), remat=(False, True),
        zero=(0, 1), moment_dtype=("f32",),
    )
    baseline = TrialConfig(batch_per_chip=1, accum_steps=1, remat=True)
    tmp = tempfile.mkdtemp(prefix="ddp_bench_tune_")
    summary = search_model(
        "gpt2-small", mesh=mesh, seq=64, space=space, baseline=baseline,
        top_k=2, warmup_steps=1, measure_steps=2, seed=0,
        tune_store=TuningStore(os.path.join(tmp, "tuned")),
    )
    out = {
        "winner": summary["winner"],
        "baseline": summary["baseline"],
        "gain_frac": summary["gain_frac"],
        "records": summary["records"],
        "store_path": summary["store_path"],
    }
    with open(out_path, "w") as fh:
        json.dump(out, fh)


def bench_autotune() -> dict:
    """Autotune done bar: on the 8-device CPU mesh, the searched config
    for GPT-2 124M beats the hand-picked default (tune_gain_frac > 0),
    and the winner is persisted for ``--autotune apply`` to replay."""
    import json as _json
    import multiprocessing as mp
    import os
    import tempfile

    root = tempfile.mkdtemp(prefix="ddp_bench_autotune_")
    out_path = os.path.join(root, "out.json")
    env = {
        "JAX_PLATFORMS": "cpu",
        "XLA_FLAGS": "--xla_force_host_platform_device_count=8",
    }
    ctx = mp.get_context("spawn")
    p = ctx.Process(target=_autotune_child, args=(out_path, env))
    p.start()
    # 3 measured candidates x (compile + 3 steps) of GPT-2 on a virtual
    # 8-device mesh: minutes on a 1-core host, like bench_zero_sharding
    p.join(timeout=1200)
    if p.is_alive():
        p.terminate()
        p.join()
        return {"error": "child timed out"}
    if p.exitcode != 0 or not os.path.exists(out_path):
        return {"error": f"child exit {p.exitcode}"}
    with open(out_path) as fh:
        out = _json.load(fh)
    w = out.get("winner") or {}
    out["tuned_step_s"] = w.get("measured_step_s")
    out["tune_gain_frac"] = out.get("gain_frac")
    out["tuner_beats_default"] = bool(
        (out.get("gain_frac") or 0.0) > 0.0
    )
    return out


def _serving_child(out_path, events_dir, env):
    """Continuous-batching vs static-batch serving on the 8-device CPU
    mesh, in a fresh interpreter (the serving acceptance target, and the
    engine's jit programs must not contend with the TPU tunnel).

    Both sides serve the SAME seeded Poisson trace on the SAME tiny
    model with greedy decoding:

    - **continuous**: the serving engine (paged KV, slot batch,
      chunked prefill) in wall-clock mode — requests admitted the step
      they arrive, retired the step they hit max_new_tokens;
    - **static**: the pre-engine serving idiom this subsystem replaces —
      collect arrivals into fixed batches of num_slots, pad every
      prompt to the trace max, run ONE compiled ``generate()`` for the
      trace-max new tokens, deliver everything at batch end.  Same
      fixed shapes (one executable, compiled before timing), so the
      contrast is pure scheduling: padding waste + tail-token waste +
      convoy TTFT, not compile counts.

    Both sides pay compilation before their timed region.  tok/s counts
    only REQUESTED tokens on both sides (the static batch generates
    trace-max tokens for every row; the excess is waste, not credit).
    """
    import os

    os.environ.update(env)
    import json
    import time

    import jax
    import jax.numpy as jnp
    import numpy as np

    from distributeddataparallel_tpu.models import TransformerLM, generate
    from distributeddataparallel_tpu.models.transformer import tiny_lm
    from distributeddataparallel_tpu.observability.events import (
        EventLog,
        events_path,
        merge_timeline,
    )
    from distributeddataparallel_tpu.observability.registry import (
        MetricsRegistry,
    )
    from distributeddataparallel_tpu.serving import (
        EngineConfig,
        InferenceEngine,
        LoadConfig,
        make_trace,
        run_load,
    )

    # Scaled-up tiny config: ~12 ms decode steps, so a reachable
    # arrival rate saturates the server (the stock tiny_lm outruns any
    # honest rate on this host and both sides just measure the trace).
    cfg = tiny_lm(
        num_layers=4, d_model=256, d_ff=1024, num_heads=8,
        max_seq_len=128,
    )
    model = TransformerLM(cfg)
    params = model.init(
        jax.random.PRNGKey(0), jnp.zeros((1, 4), jnp.int32)
    )["params"]

    # Saturating load (arrivals outpace drain: ~1200 tok/s offered vs
    # ~675 tok/s engine capacity measured at full slots): at a gentle
    # rate both sides are arrival-bound and tok/s measures the trace,
    # not the server; under saturation the static batch's padding waste
    # (every row generates the trace-max tokens) shows up as the real
    # tok/s gap while the convoy effect shows up in TTFT.
    lcfg = LoadConfig(
        rate_rps=120.0, duration_s=1.0, prompt_len=(4, 24),
        output_len=(4, 16), vocab_size=cfg.vocab_size, seed=0,
    )
    trace = make_trace(lcfg)
    n_slots = 8

    # -- continuous batching (the engine) -----------------------------
    os.makedirs(events_dir, exist_ok=True)
    events = EventLog(events_path(events_dir, 0), 0)
    events.emit("run_start", argv=["bench_serving"], role="serve")
    registry = MetricsRegistry()
    engine = InferenceEngine(
        model, params,
        EngineConfig(num_slots=n_slots, num_blocks=64, block_size=16,
                     prefill_chunk=32),
        events=events, registry=registry,
    )
    # Warmup: compile both programs (prefill + decode) outside the
    # timed region, leaving the engine drained.
    engine.submit(np.arange(4, dtype=np.int32) % cfg.vocab_size, 4)
    engine.run()
    engine.completed.clear()  # warmup must not count in the summary
    t0 = time.perf_counter()
    cb = run_load(engine, trace)
    cb_wall = time.perf_counter() - t0
    events.emit("metrics", snapshot=registry.snapshot())
    events.emit("run_end", status="ok")
    events.close()
    merge_timeline(events_dir)

    # -- static batching (generate() on fixed shapes) -----------------
    p_max = max(len(r["prompt"]) for r in trace)
    n_max = max(r["max_new_tokens"] for r in trace)
    pad_prompt = np.zeros((n_slots, p_max), np.int32)
    warm = generate(model, params, jnp.asarray(pad_prompt), n_max)
    assert int(jnp.sum(warm)) >= 0  # compile + fence

    t0 = time.perf_counter()
    done_at = {}
    for lo in range(0, len(trace), n_slots):
        group = trace[lo:lo + n_slots]
        # The batch cannot launch before its last member arrives.
        launch = max(r["arrival_s"] for r in group)
        now = time.perf_counter() - t0
        if now < launch:
            time.sleep(launch - now)
        batch = np.zeros((n_slots, p_max), np.int32)
        for i, r in enumerate(group):
            batch[i, :len(r["prompt"])] = r["prompt"]
        out = generate(model, params, jnp.asarray(batch), n_max)
        assert int(jnp.sum(out)) >= 0  # fence: tokens delivered now
        end = time.perf_counter() - t0
        for r in group:
            done_at[id(r)] = end
    static_wall = time.perf_counter() - t0
    static_tokens = sum(r["max_new_tokens"] for r in trace)
    static_ttft = sorted(
        done_at[id(r)] - r["arrival_s"] for r in trace
    )

    def pct(vals, q):
        return float(np.percentile(vals, q)) if vals else None

    out = {
        "requests": len(trace),
        "completed": cb["completed"],
        "num_slots": n_slots,
        "rate_rps": lcfg.rate_rps,
        "serve_tok_s": cb["serve_tok_s"],
        "serve_p50_ttft_s": cb["serve_p50_ttft_s"],
        "serve_p99_ttft_s": cb["serve_p99_ttft_s"],
        "cb_wall_s": round(cb_wall, 3),
        "static_tok_s": round(static_tokens / static_wall, 1),
        "static_p50_ttft_s": round(pct(static_ttft, 50), 4),
        "static_p99_ttft_s": round(pct(static_ttft, 99), 4),
        "static_wall_s": round(static_wall, 3),
        "cb_tok_s_speedup": round(
            cb["serve_tok_s"] / (static_tokens / static_wall), 3
        ),
        "cb_p99_ttft_improvement": round(
            pct(static_ttft, 99) / max(cb["serve_p99_ttft_s"], 1e-9), 3
        ),
        "preemptions": cb["preemptions"],
        "evictions": cb["evictions"],
    }
    with open(out_path, "w") as fh:
        json.dump(out, fh)


def _integrity_child(out_path, env):
    """Digest-on vs digest-off step timing in a fresh 8-device CPU-mesh
    interpreter (same isolation as the other CPU-mesh children: the
    acceptance target is the fake-device mesh, not the TPU tunnel).

    Headline arm replicates dpp.py's production dispatch: cadence-length
    step windows where the single cadence step runs the digest-armed
    program and the rest run the bit-identical plain program, against
    plain-only windows.  A cadence-1 worst case (EVERY timed step pays
    the digest + all_gather) rides along as detail.  Tiny model on
    purpose: a 1-core host runs a GPT-2 step in ~40 s, which cannot
    resolve a 1% delta; a ~100 ms step can.  The two arms run
    INTERLEAVED and the minimum per-arm time is compared (min-of-reps
    is robust to the host's additive noise, and interleaving cancels
    thermal/load drift that back-to-back loops would bake into one
    side).  Also runs one flip round-trip as a correctness canary so
    the perf number can never come from a digest that stopped
    detecting.
    """
    import os

    os.environ.update(env)
    import json
    import time

    import jax
    import jax.numpy as jnp
    import numpy as np
    import optax

    import distributeddataparallel_tpu as ddp
    from distributeddataparallel_tpu.data.loader import shard_batch
    from distributeddataparallel_tpu.models import TransformerLM, tiny_lm
    from distributeddataparallel_tpu.ops import lm_cross_entropy
    from distributeddataparallel_tpu.training import integrity as integ
    from distributeddataparallel_tpu.training.train_step import (
        make_train_step,
    )

    SEQ = 64
    mesh = ddp.make_mesh(("data",))
    n = len(jax.devices())
    cfg = tiny_lm(max_seq_len=SEQ, num_layers=4, d_model=64, d_ff=128)
    model = TransformerLM(cfg)

    def loss_fn(p, batch, rng):
        logits = model.apply({"params": p}, batch["tokens"][:, :-1],
                             deterministic=True)
        return lm_cross_entropy(logits, batch["tokens"][:, 1:]), {}

    params = jax.jit(model.init)(
        jax.random.PRNGKey(0), jnp.zeros((1, SEQ), jnp.int32)
    )["params"]
    state = ddp.broadcast_params(
        ddp.TrainState.create(
            apply_fn=model.apply, params=params, tx=optax.adamw(3e-4)
        ),
        mesh,
    )
    npr = np.random.default_rng(0)
    batch = shard_batch(
        {"tokens": npr.integers(
            0, cfg.vocab_size, size=(2 * n, SEQ + 1)
        ).astype(np.int32)},
        mesh,
    )
    key = jax.random.PRNGKey(0)

    # Both arms arm the nonfinite guard — the recommended production
    # config (dpp.py runs --nan-guard alongside --integrity-every), and
    # the config whose cost model the train step optimizes for: the SDC
    # verdict folds into the guard's existing whole-state skip select,
    # so the digest-on arm's marginal cost is the cadence-gated digest
    # + all_gather alone, which is exactly what this A/B measures.
    CADENCE = 50  # a representative production cadence
    step_off = make_train_step(
        loss_fn, mesh=mesh, donate=False, nonfinite_guard=True
    )
    step_on1 = make_train_step(
        loss_fn, mesh=mesh, donate=False, nonfinite_guard=True,
        integrity_every=1,
    )
    step_onN = make_train_step(
        loss_fn, mesh=mesh, donate=False, nonfinite_guard=True,
        integrity_every=CADENCE,
    )

    def once(step, s_in):
        t0 = time.perf_counter()
        s, m = step(s_in, batch, key)
        jax.block_until_ready(s)
        return time.perf_counter() - t0, (s, m)

    for _ in range(2):  # compile + warm all three programs
        once(step_off, state)
        once(step_on1, state)
        once(step_onN, state)

    # Under dpp.py's dual-program dispatch the CADENCE-1 off-cadence
    # steps ARE the digest-off executable — their marginal cost is zero
    # by construction, not by measurement.  What a production window
    # pays extra is (a) the one cadence step running the digest-armed
    # program instead of the plain one and (b) the following plain step
    # consuming state produced by a different executable (a possible
    # relayout at the program switch).  Both are single-step deltas, so
    # they are measured as tightly-interleaved singles (min-of-reps
    # kills the host's additive noise; whole-window A/B timing on this
    # box has a ~3% noise floor that swamps a 0.2% effect) and
    # amortized over the cadence for the headline.
    s_digest = once(step_onN, state)[1][0]  # digest-program-made state
    m_on = once(step_on1, state)[1][1]      # clean-run cadence metrics
    REPS = 25
    times = {"plain": [], "digest": [], "switch": []}
    arms = [
        ("plain", step_off, state),
        ("digest", step_onN, state),
        ("switch", step_off, s_digest),
    ]
    for i in range(REPS):
        for name, fn, s_in in arms[i % 3:] + arms[: i % 3]:
            t, _ = once(fn, s_in)
            times[name].append(t)
    w_off = min(times["plain"])
    w_on = min(times["digest"])
    switch_s = max(0.0, min(times["switch"]) - w_off)
    amortized = ((w_on - w_off) + switch_s) / (CADENCE * w_off)

    # canary: the timed digest still detects a real flip
    flipped = integ.apply_bitflip(state, rank=3, mesh=mesh)
    _, m = step_on1(flipped, batch, key)
    mat = np.asarray(jax.device_get(m["sdc_digest"]))
    verdict = integ.vote(mat)

    with open(out_path, "w") as fh:
        json.dump({
            "cadence": CADENCE,
            "integrity_overhead_frac": round(amortized, 5),
            "digest_step_s_off": round(w_off, 5),
            "digest_step_s_on": round(w_on, 5),
            "digest_step_overhead_frac": round((w_on - w_off) / w_off, 4),
            "program_switch_s": round(switch_s, 5),
            "clean_mismatch": float(m_on["sdc_mismatch"]),
            "canary_detected": bool(
                not verdict.ok and verdict.corrupt == (3,)
            ),
        }, fh)


def bench_integrity() -> dict:
    """SDC-digest overhead (--integrity-every): the claim is <= 1%
    amortized step-time cost at a production cadence.  Headline
    ``integrity_overhead_frac`` compares cadence-length step windows
    under dpp.py's dual-program dispatch (exactly one digest step per
    window, plain program elsewhere) and is gated lower-better by
    perf_gate's ``_frac`` suffix rule; the cadence-1 worst case rides
    along as ``digest_step_overhead_frac``."""
    import json as _json
    import multiprocessing as mp
    import os
    import tempfile

    root = tempfile.mkdtemp(prefix="ddp_bench_integrity_")
    out_path = os.path.join(root, "out.json")
    env = {
        "JAX_PLATFORMS": "cpu",
        "XLA_FLAGS": "--xla_force_host_platform_device_count=8",
    }
    ctx = mp.get_context("spawn")
    p = ctx.Process(target=_integrity_child, args=(out_path, env))
    p.start()
    p.join(timeout=900)
    if p.is_alive():
        p.terminate()
        p.join()
        return {"error": "child timed out"}
    if p.exitcode != 0 or not os.path.exists(out_path):
        return {"error": f"child exit {p.exitcode}"}
    with open(out_path) as fh:
        out = _json.load(fh)
    out["within_1pct"] = (
        out.get("integrity_overhead_frac", 1.0) <= 0.01
        and out.get("canary_detected", False)
        and out.get("clean_mismatch") == 0.0
    )
    return out



def bench_serving() -> dict:
    """Serving done bar: on the 8-device CPU mesh, the continuous-
    batching engine beats static-batch generate() on the same Poisson
    trace in BOTH tok/s and p99 TTFT; headline keys serve_tok_s /
    serve_p99_ttft_s are gated by perf_gate."""
    import json as _json
    import multiprocessing as mp
    import os
    import tempfile

    root = tempfile.mkdtemp(prefix="ddp_bench_serve_")
    out_path = os.path.join(root, "out.json")
    env = {
        "JAX_PLATFORMS": "cpu",
        "XLA_FLAGS": "--xla_force_host_platform_device_count=8",
    }
    ctx = mp.get_context("spawn")
    p = ctx.Process(
        target=_serving_child,
        args=(out_path, os.path.join(root, "events"), env),
    )
    p.start()
    p.join(timeout=600)
    if p.is_alive():
        p.terminate()
        p.join()
        return {"error": "child timed out"}
    if p.exitcode != 0 or not os.path.exists(out_path):
        return {"error": f"child exit {p.exitcode}"}
    with open(out_path) as fh:
        out = _json.load(fh)
    out["cb_beats_static"] = bool(
        out.get("cb_tok_s_speedup", 0) > 1.0
        and out.get("cb_p99_ttft_improvement", 0) > 1.0
    )
    return out


def _serving_fastpath_child(out_path, env):
    """Serving fast path (refcounted radix prefix cache + speculative
    decoding) vs the plain engine, in a fresh interpreter.

    Both sides serve the SAME seeded shared-prefix Zipf trace (a pool
    of hot system-prompt-like prefixes, Zipf rank weights, random
    suffixes) on the SAME scaled-up tiny model, wall-clock, greedy:

    - **base**: the engine as benched above — every admitted request
      prefills its full context, one token per decode dispatch;
    - **fast**: ``prefix_cache=True`` maps the shared prefix blocks
      out of the radix cache (skipping their prefill FLOPs entirely)
      and ``spec_k=4`` drafts 4 tokens per slot per step through the
      fixed-shape verify program, emitting every accepted prefix
      token in one dispatch.

    Greedy outputs are bitwise-identical by construction (pinned by
    tests/test_serving.py), so the contrast is pure scheduling/compute:
    avoided prefill chunks + multi-token decode steps.  Headline keys
    spec_tok_s_speedup / prefix_hit_frac / prefill_flops_avoided_frac
    gate higher-is-better; fastpath_p99_ttft_s lower-is-better.
    """
    import os

    os.environ.update(env)
    import json
    import time

    import jax
    import jax.numpy as jnp
    import numpy as np

    from distributeddataparallel_tpu.models import TransformerLM
    from distributeddataparallel_tpu.models.transformer import tiny_lm
    from distributeddataparallel_tpu.serving import (
        EngineConfig,
        InferenceEngine,
        LoadConfig,
        make_trace,
        run_load,
    )

    cfg = tiny_lm(
        num_layers=4, d_model=256, d_ff=1024, num_heads=8,
        max_seq_len=128,
    )
    model = TransformerLM(cfg)
    params = model.init(
        jax.random.PRNGKey(0), jnp.zeros((1, 4), jnp.int32)
    )["params"]

    # Long shared prefixes (48 of 56-63 prompt tokens) + saturating
    # arrivals + long generations (48-64): the base side pays chunked
    # prefill for every hot prefix AND one dispatch per output token —
    # the radix cache attacks the former, speculation the latter.  The
    # two compose: the cache alone leaves the run decode-bound, which
    # is exactly the regime where multi-token verify dispatches pay.
    lcfg = LoadConfig(
        rate_rps=60.0, duration_s=1.0, prompt_len=(56, 63),
        output_len=(48, 64), vocab_size=cfg.vocab_size, seed=0,
        prefix_pool=4, prefix_len=48, zipf_alpha=1.1,
    )
    trace = make_trace(lcfg)

    def run_side(prefix_cache, spec_k):
        engine = InferenceEngine(
            model, params,
            EngineConfig(num_slots=8, num_blocks=96, block_size=16,
                         prefill_chunk=32, prefix_cache=prefix_cache,
                         spec_k=spec_k),
        )
        # Warmup compiles every program this side dispatches (prefill +
        # decode or verify) outside the timed region; the warmup
        # request's stats must not count.
        engine.submit(np.arange(40, dtype=np.int32) % cfg.vocab_size, 4)
        engine.run()
        engine.completed.clear()
        for attr in ("prefix_admits", "prefix_hits", "prefix_hit_tokens",
                     "prefix_ctx_tokens", "cow_copies", "spec_rows",
                     "spec_drafted", "spec_accepted"):
            setattr(engine, attr, 0)
        t0 = time.perf_counter()
        out = run_load(engine, trace)
        out["wall_s"] = round(time.perf_counter() - t0, 3)
        return out

    base = run_side(False, 0)
    fast = run_side(True, 4)

    out = {
        "requests": len(trace),
        "completed": fast["completed"],
        "rate_rps": lcfg.rate_rps,
        "prefix_pool": lcfg.prefix_pool,
        "prefix_len": lcfg.prefix_len,
        "zipf_alpha": lcfg.zipf_alpha,
        "base_tok_s": round(base["serve_tok_s"], 1),
        "base_p50_ttft_s": round(base["serve_p50_ttft_s"], 4),
        "base_p99_ttft_s": round(base["serve_p99_ttft_s"], 4),
        "base_wall_s": base["wall_s"],
        "fast_tok_s": round(fast["serve_tok_s"], 1),
        "fast_p50_ttft_s": round(fast["serve_p50_ttft_s"], 4),
        "fastpath_p99_ttft_s": round(fast["serve_p99_ttft_s"], 4),
        "fast_wall_s": fast["wall_s"],
        "spec_tok_s_speedup": round(
            fast["serve_tok_s"] / max(base["serve_tok_s"], 1e-9), 3
        ),
        "fastpath_p99_ttft_improvement": round(
            base["serve_p99_ttft_s"]
            / max(fast["serve_p99_ttft_s"], 1e-9), 3
        ),
        "prefix_hit_frac": round(fast.get("prefix_hit_frac", 0.0), 3),
        "prefill_flops_avoided_frac": round(
            fast.get("prefill_flops_avoided_frac", 0.0), 3
        ),
        "spec_accept_mean": round(fast.get("spec_accept_mean", 0.0), 3),
        "cow_copies": fast.get("cow_copies", 0),
        "preemptions": fast["preemptions"],
        "evictions": fast["evictions"],
    }
    with open(out_path, "w") as fh:
        json.dump(out, fh)


def bench_serving_fastpath() -> dict:
    """Fast-path done bar: on the shared-prefix Zipf trace the engine
    with prefix cache + speculation sustains >1.5x the plain engine's
    tok/s and drops p99 TTFT, with >0.5 of admissions hitting the
    radix cache; headline keys spec_tok_s_speedup / prefix_hit_frac /
    prefill_flops_avoided_frac are gated higher-is-better."""
    import json as _json
    import multiprocessing as mp
    import os
    import tempfile

    root = tempfile.mkdtemp(prefix="ddp_bench_fastpath_")
    out_path = os.path.join(root, "out.json")
    env = {
        "JAX_PLATFORMS": "cpu",
        "XLA_FLAGS": "--xla_force_host_platform_device_count=8",
    }
    ctx = mp.get_context("spawn")
    p = ctx.Process(target=_serving_fastpath_child, args=(out_path, env))
    p.start()
    p.join(timeout=600)
    if p.is_alive():
        p.terminate()
        p.join()
        return {"error": "child timed out"}
    if p.exitcode != 0 or not os.path.exists(out_path):
        return {"error": f"child exit {p.exitcode}"}
    with open(out_path) as fh:
        out = _json.load(fh)
    out["fastpath_beats_base"] = bool(
        out.get("spec_tok_s_speedup", 0) > 1.5
        and out.get("fastpath_p99_ttft_improvement", 0) > 1.0
        and out.get("prefix_hit_frac", 0) > 0.5
    )
    return out


def _serving_fleet_child(out_path, env):
    """Disaggregated fleet (1 prefill + 2 decode engines, KV-block
    handoff, session-affinity router) vs 3 identical MONOLITHIC engines
    behind the same router, in a fresh interpreter.

    Both sides serve the SAME seeded multi-turn trace (every base
    request seeds a 2-turn session whose follow-up extends the prior
    prompt) on the SAME scaled-up tiny model, wall-clock, greedy —
    ``ServingFleet`` with ``prefill=0`` IS the monolithic baseline
    (the router load-balances decode engines that each prefill their
    own requests, one chunk per step, interleaved with decode).

    Why disaggregation wins here: (a) TTFT decouples from decode-slot
    occupancy — the first token is produced on the prefill tier, so a
    full decode batch of long generations no longer delays a new
    prompt's first token; (b) the prefill tier runs 4 chunks per step
    with no decode batch to protect; (c) decode work concentrates on
    fewer engines, so each fixed-shape decode dispatch carries more
    active slots (tokens per dispatch), which is the whole cost model
    of the padded (num_slots, 1) program.

    A THIRD run re-serves the trace on the fleet with one decode
    engine killed mid-drive: its requests (and in-flight handoffs to
    it) must drain-and-requeue onto the survivor with zero dropped —
    that run feeds ``dropped_req_total`` (hard-zero in perf_gate), not
    the perf headlines.
    """
    import os

    os.environ.update(env)
    import json
    import time

    import jax
    import jax.numpy as jnp
    import numpy as np

    from distributeddataparallel_tpu.models import TransformerLM
    from distributeddataparallel_tpu.models.transformer import tiny_lm
    from distributeddataparallel_tpu.serving import (
        EngineConfig,
        LoadConfig,
        make_trace,
        run_load,
    )
    from distributeddataparallel_tpu.serving.fleet import (
        FleetConfig,
        ServingFleet,
    )

    cfg = tiny_lm(
        num_layers=4, d_model=256, d_ff=1024, num_heads=8,
        max_seq_len=256,
    )
    model = TransformerLM(cfg)
    params = model.init(
        jax.random.PRNGKey(0), jnp.zeros((1, 4), jnp.int32)
    )["params"]
    ecfg = EngineConfig(
        num_slots=8, num_blocks=128, block_size=16, prefill_chunk=32,
        prefix_cache=True,
    )
    # Long prompts (prefill-heavy admissions) + long outputs (decode
    # occupancy that delays monolithic admissions) + 2-turn sessions
    # (affinity traffic for the router) at a saturating rate.
    lcfg = LoadConfig(
        rate_rps=30.0, duration_s=1.0, prompt_len=(72, 96),
        output_len=(32, 48), vocab_size=cfg.vocab_size, seed=0,
        turns=2, turn_gap_s=0.3, turn_tokens=(8, 16),
    )
    trace = make_trace(lcfg)

    def build(prefill, decode, events=None):
        fleet = ServingFleet(
            model, params, ecfg,
            FleetConfig(prefill=prefill, decode=decode,
                        prefill_chunks_per_step=4),
            events=events,
        )
        # Warm every engine's programs outside the timed region, then
        # reset the stats the summary reads.  Each jitted program lives
        # per-ENGINE, so the warmup must walk every compile the timed
        # trace will hit: prompt lengths spanning the trace's handoff
        # block counts (set_pool_blocks compiles per count), and
        # sessioned follow-up turns so the DECODE tier's prefill
        # program compiles too (affinity hits prefill there — injected
        # requests alone never would).
        rng = np.random.default_rng(123)
        lens = [int(x) for x in np.linspace(
            lcfg.prompt_len[0],
            lcfg.prompt_len[1] + lcfg.turn_tokens[1] + 1,
            max(8, 2 * (prefill + decode)),
        )]
        for i, n in enumerate(lens):
            p = rng.integers(0, cfg.vocab_size, n).tolist()
            fleet.submit(p, 4, session=f"warm-{i}")
            while fleet.has_work():
                fleet.step()
            fleet.submit(
                p + rng.integers(0, cfg.vocab_size, 8).tolist(), 4,
                session=f"warm-{i}",
            )
        while fleet.has_work():
            fleet.step()
        fleet.completed.clear()
        fleet.dropped.clear()
        fleet.handoffs = 0
        fleet.handoff_bytes = 0
        fleet.handoff_s_sum = 0.0
        fleet.router.routed = 0
        fleet.router.affinity_hits = 0
        fleet.router._affinity.clear()
        for eng in fleet.engines.values():
            eng.completed.clear()
            for attr in ("prefix_admits", "prefix_hits",
                         "prefix_hit_tokens", "prefix_ctx_tokens",
                         "cow_copies"):
                setattr(eng, attr, 0)
        return fleet

    def timed(fleet):
        t0 = time.perf_counter()
        out = run_load(fleet, trace)
        out["wall_s"] = round(time.perf_counter() - t0, 3)
        return out

    mono = timed(build(0, 3))
    # The disagg run records its span timeline so the TTFT
    # decomposition headlines come from the SAME trace the perf
    # numbers do (warmup fids are filtered out below).
    from distributeddataparallel_tpu.observability import critical_path
    from distributeddataparallel_tpu.observability.events import (
        EventLog,
        read_events,
    )

    span_log_path = os.path.join(
        os.path.dirname(out_path), "events-fleet.jsonl"
    )
    span_log = EventLog(span_log_path, "bench-fleet")
    fleet = build(1, 2, events=span_log)
    disagg = timed(fleet)
    span_log.close()
    timed_fids = set(fleet.completed)
    decomps = [
        d for d in critical_path.request_decompositions(
            read_events(span_log_path)
        )
        if d["req"] in timed_fids
    ]
    droll = critical_path.ttft_rollup(decomps)

    # Robustness run: same trace, one decode engine killed mid-drive.
    kfleet = build(1, 2)
    i = 0
    t0 = time.perf_counter()
    killed = False
    while i < len(trace) or kfleet.has_work():
        now = time.perf_counter() - t0
        while i < len(trace) and trace[i]["arrival_s"] <= now:
            r = trace[i]
            kfleet.submit(
                r["prompt"], r["max_new_tokens"],
                session=r.get("session"),
            )
            i += 1
        if not killed and i >= len(trace) // 2:
            kfleet.kill_engine("decode-1")
            killed = True
        if kfleet.has_work():
            kfleet.step()
        else:
            time.sleep(0.0002)

    out = {
        "requests": len(trace),
        "completed": disagg["completed"],
        "rate_rps": lcfg.rate_rps,
        "turns": lcfg.turns,
        "mono_tok_s": round(mono["serve_tok_s"], 1),
        "mono_p50_ttft_s": round(mono["serve_p50_ttft_s"], 4),
        "mono_p99_ttft_s": round(mono["serve_p99_ttft_s"], 4),
        "mono_wall_s": mono["wall_s"],
        "fleet_tok_s": round(disagg["serve_tok_s"], 1),
        "fleet_p50_ttft_s": round(disagg["serve_p50_ttft_s"], 4),
        "fleet_p99_ttft_s": round(disagg["serve_p99_ttft_s"], 4),
        "fleet_wall_s": disagg["wall_s"],
        "fleet_tok_s_speedup": round(
            disagg["serve_tok_s"] / max(mono["serve_tok_s"], 1e-9), 3
        ),
        "fleet_p99_ttft_improvement": round(
            mono["serve_p99_ttft_s"]
            / max(disagg["serve_p99_ttft_s"], 1e-9), 3
        ),
        "handoffs": disagg["handoffs"],
        "handoff_bytes": disagg["handoff_bytes"],
        "handoff_s": round(disagg["handoff_s"], 5),
        "re_handoff_blocks": disagg["re_handoff_blocks"],
        "affinity_hits": disagg["affinity_hits"],
        "affinity_frac": round(
            disagg["affinity_hits"] / max(disagg["routed"], 1), 3
        ),
        "tiers": disagg.get("tiers"),
        # TTFT decomposition over the disagg run's span timeline:
        # share fractions + the span-tree self-consistency error
        # (all lower-better in perf_gate via _share_frac/_decomp_err).
        "ttft_queue_share_frac": round(
            droll.get("ttft_queue_share_frac", 0.0), 4
        ),
        "ttft_handoff_share_frac": round(
            droll.get("ttft_handoff_share_frac", 0.0), 4
        ),
        "ttft_decomp_err_frac": round(
            droll.get("ttft_decomp_err_frac", 1.0), 4
        ),
        "ttft_decomp_requests": droll.get("requests", 0),
        # Kill run (robustness, not perf): every request must still
        # complete — dropped_req_total is hard-zero in perf_gate.
        "dropped_req_total": len(kfleet.dropped),
        "kill_completed": len(kfleet.completed),
        "kill_requeued": kfleet.requeued,
        "kill_handoffs": kfleet.handoffs,
    }
    with open(out_path, "w") as fh:
        json.dump(out, fh)


def bench_serving_fleet() -> dict:
    """Fleet done bar: the 1:2 disaggregated fleet beats 3 monolithic
    engines on p99 TTFT while holding tokens/s, and the engine-kill
    run drains with zero dropped requests.  Headline keys
    fleet_tok_s_speedup (higher-better via _speedup$), fleet_p99_ttft_s
    / handoff_s (lower-better via _s$), dropped_req_total (lower-better
    + hard-zero), plus the TTFT decomposition from the disagg run's
    span timeline: ttft_queue_share_frac / ttft_handoff_share_frac /
    ttft_decomp_err_frac (all lower-better via the _share_frac /
    _decomp_err_frac row)."""
    import json as _json
    import multiprocessing as mp
    import os
    import tempfile

    root = tempfile.mkdtemp(prefix="ddp_bench_fleet_")
    out_path = os.path.join(root, "out.json")
    env = {
        "JAX_PLATFORMS": "cpu",
        "XLA_FLAGS": "--xla_force_host_platform_device_count=8",
    }
    ctx = mp.get_context("spawn")
    p = ctx.Process(target=_serving_fleet_child, args=(out_path, env))
    p.start()
    p.join(timeout=600)
    if p.is_alive():
        p.terminate()
        p.join()
        return {"error": "child timed out"}
    if p.exitcode != 0 or not os.path.exists(out_path):
        return {"error": f"child exit {p.exitcode}"}
    with open(out_path) as fh:
        out = _json.load(fh)
    out["fleet_beats_mono"] = bool(
        out.get("fleet_tok_s_speedup", 0) >= 1.0
        and out.get("fleet_p99_ttft_improvement", 0) > 1.0
        and out.get("dropped_req_total", 1) == 0
        and out.get("kill_completed", 0) == out.get("requests", -1)
    )
    return out


def _run(fn, label: str) -> dict:
    """Run a bench section; one retry shields the driver's single shot
    from transient tunnel/compile hiccups.  Failures degrade to an error
    record instead of killing the whole artifact."""
    for attempt in (1, 2):
        t0 = time.perf_counter()
        try:
            out = fn()
            out["wall_s"] = round(time.perf_counter() - t0, 1)
            return out
        except Exception as e:  # noqa: BLE001
            import sys
            import traceback

            traceback.print_exc()
            print(f"[bench] {label} attempt {attempt} failed: {e}",
                  file=sys.stderr)
    return {"error": f"{label} failed twice"}


def main() -> None:
    import os

    import jax

    # Persistent compilation cache: compile times through the driver's
    # TPU tunnel are large and variable (minutes); warming the cache here
    # makes reruns (and the driver's timed run) start hot.
    jax.config.update(
        "jax_compilation_cache_dir",
        os.path.join(os.path.dirname(os.path.abspath(__file__)), ".jax_cache"),
    )
    jax.config.update("jax_persistent_cache_min_compile_time_secs", 1.0)

    dev = jax.devices()[0]
    resnet = _run(bench_resnet50, "resnet50")
    gpt2 = _run(bench_gpt2, "gpt2")
    llama = _run(bench_llama, "llama")
    decode = _run(bench_decode, "decode")
    moe = _run(bench_moe_scaling, "moe_scaling")
    cp_ring = _run(bench_cp_ring, "cp_ring")
    overlap = _run(bench_overlap, "overlap")
    pp_zb = _run(bench_pipeline_zb, "pipeline_zb")
    pp_bubble = pp_zb.get("analytic", {})  # roofline column rides along
    input_pipe = _run(bench_input_pipeline, "input_pipeline")
    warm = _run(bench_warm_start, "warm_start")
    elastic = _run(bench_elastic_resize, "elastic_resize")
    obs = _run(bench_observability, "observability")
    integrity = _run(bench_integrity, "integrity")
    zshard = _run(bench_zero_sharding, "zero_sharding")
    serving = _run(bench_serving, "serving")
    fastpath = _run(bench_serving_fastpath, "serving_fastpath")
    fleet = _run(bench_serving_fleet, "serving_fleet")
    autotune = _run(bench_autotune, "autotune")
    # Config 3's done bar: can the host pipeline feed the device?
    if "host_gather_img_s" in input_pipe and "img_s_chip" in resnet:
        dev_rate = resnet["img_s_chip"] * len(jax.devices())
        input_pipe["device_img_s"] = round(dev_rate, 1)
        input_pipe["host_over_device"] = round(
            input_pipe["host_gather_img_s"] / max(dev_rate, 1e-9), 3
        )

    # Token-pipeline done-bar (mirrors the image one above).
    if "token_gather_tok_s" in input_pipe and "tokens_s_chip" in gpt2:
        tok_dev = gpt2["tokens_s_chip"] * len(jax.devices())
        input_pipe["device_tok_s"] = round(tok_dev, 1)
        input_pipe["token_host_over_device"] = round(
            input_pipe["token_gather_tok_s"] / max(tok_dev, 1e-9), 3
        )

    img_s_chip = resnet.get("img_s_chip", 0.0)
    target = TARGET_FRACTION * A100_DDP_RESNET50_IMG_S
    full = {
        "metric": "img/s/chip (resnet50_imagenet_dp)",
        "value": img_s_chip,
        "unit": "img/s/chip",
        "vs_baseline": round(img_s_chip / target, 4),
        "extras": {
            "peaks": _device_peaks(),
            "device_kind": dev.device_kind,
            "platform": dev.platform,
            "n_devices": len(jax.devices()),
            "resnet50": resnet,
            "gpt2_124m": gpt2,
            "llama_0p6b": llama,
            "decode_gpt2": decode,
            "moe_token_choice": moe,
            "cp_ring_block": cp_ring,
            "overlap_gpt2_dp": overlap,
            "pipeline_1f1b_bubble": pp_bubble,
            "pipeline_zb": pp_zb,
            "input_pipeline": input_pipe,
            "warm_start": warm,
            "elastic_resize": elastic,
            "observability": obs,
            "integrity": integrity,
            "zero_sharding": zshard,
            "serving": serving,
            "serving_fastpath": fastpath,
            "serving_fleet": fleet,
            "autotune": autotune,
        },
    }
    # Full detail: stdout (live readers) + a file next to this script —
    # the driver persists only a 2 KB stdout TAIL, which round 4 proved
    # loses the headline sections (VERDICT r4 missing 3).
    print(json.dumps(full))
    detail_path = os.path.join(
        os.path.dirname(os.path.abspath(__file__)), "BENCH_DETAIL.json"
    )
    with open(detail_path, "w") as fh:
        json.dump(full, fh, indent=1)

    # LAST line: a compact headline summary sized to always fit the
    # driver's tail, so every README perf claim is auditable from
    # BENCH_r{N}.json alone.
    def _sched(rep):
        if not isinstance(rep, dict):
            return {"error": "missing"}
        if "error" in rep:
            return {"error": str(rep["error"])[:60]}
        return {
            "windows": rep["n_async_windows"],
            "sync": rep["n_sync_collectives"],
            "frac_compute": rep["overlapped_frac_of_compute"],
            "async_bytes_frac": rep["async_bytes_frac"],
        }

    headline = {
        "metric": full["metric"],
        "value": img_s_chip,
        "unit": "img/s/chip",
        "vs_baseline": full["vs_baseline"],
        "headline": {
            "device": dev.device_kind,
            "resnet50_img_s_chip": img_s_chip,
            "resnet50_mfu": resnet.get("mfu_est"),
            "gpt2_tok_s_chip": gpt2.get("tokens_s_chip"),
            "gpt2_mfu": gpt2.get("mfu_est"),
            "gpt2_attn_winner": gpt2.get("attn_winner"),
            "llama_tok_s_chip": llama.get("tokens_s_chip"),
            "llama_mfu": llama.get("mfu_est"),
            "decode_tok_s_chip_b256": (
                decode.get("per_batch", {}).get("256", {})
                .get("decode_tokens_s_chip")
            ),
            "decode_hbm_util_b8": decode.get("hbm_util_b8"),
            "decode_int8_llama_step_speedup": decode.get(
                "int8_llama_0p6b", {}
            ).get("step_speedup_int8"),
            "decode_int8_gpt2_b8_step_speedup": decode.get(
                "int8_b8", {}
            ).get("step_speedup_int8"),
            "moe_e16_over_e4": moe.get("e16_over_e4"),
            "moe_roofline": moe.get("e16_over_e4_weight_traffic_roofline"),
            "moe_ep_shard_frac_measured": moe.get("ep_memory", {}).get(
                "measured_expert_shard_frac"
            ),
            "flash_vs_xla_block_speedup": cp_ring.get("flash_speedup"),
            "overlap_real_gpt2": _sched(
                overlap.get("real_step_schedule_gpt2")
            ),
            "overlap_real_llama": _sched(
                overlap.get("real_step_schedule_llama")
            ),
            "pp_interleaved_bubble_v4_over_v1": (
                pp_bubble.get("stages8_mb32", {}).get("v4_over_v1_bubble")
            ),
            # flat keys (perf_gate contract): *_frac / *_s suffixes make
            # both lower-is-better; measured from the compiled zb
            # schedule's phase counters, not the tick model
            "zb_bubble_frac": pp_zb.get("zb_bubble_frac"),
            "zb_step_s": pp_zb.get("zb_step_s"),
            "zb_beats_1f1b": pp_zb.get("zb_beats_1f1b_analytic"),
            "input_host_gather_img_s": input_pipe.get("host_gather_img_s"),
            "input_host_over_device": input_pipe.get("host_over_device"),
            "token_gather_tok_s": input_pipe.get("token_gather_tok_s"),
            "token_host_over_device": input_pipe.get(
                "token_host_over_device"
            ),
            "warm_start_s": {
                "cold": warm.get("cold", {}).get("acquire_s"),
                "cache": warm.get("cache_hit", {}).get("acquire_s"),
                "aot": warm.get("aot", {}).get("acquire_s"),
                "aot_x": warm.get("aot_speedup"),
            },
            # flat on purpose (perf_gate): resize_downtime_s is
            # lower-better via _s$; restart_reclaimed_s is the seconds
            # the elastic path gave back vs a cold restart — HIGHER is
            # better (_HIGHER_BETTER's reclaimed_s$ override)
            "resize_downtime_s": elastic.get("resize_downtime_s"),
            "restart_reclaimed_s": elastic.get("restart_reclaimed_s"),
            # flat on purpose (perf_gate): the _frac suffix makes the
            # SDC-digest step-time cost lower-is-better; measured at
            # cadence 1, the worst case — production cadence N pays 1/N
            "integrity_overhead_frac": integrity.get(
                "integrity_overhead_frac"
            ),
            "integrity_ok": integrity.get("within_1pct"),
            "obs": {
                "ovh": obs.get("overhead_frac_micro"),
                "sync0": obs.get("zero_extra_syncs"),
                "ok": obs.get("within_2pct"),
            },
            # flat keys on purpose: perf_gate gates top-level numerics,
            # and the *_bytes / *_s suffixes make them lower-is-better
            "z2_hwm_bytes": zshard.get("zero2", {}).get(
                "perdevice_hwm_bytes"
            ),
            "z3_hwm_bytes": zshard.get("zero3", {}).get(
                "perdevice_hwm_bytes"
            ),
            "z2_step_s": zshard.get("zero2", {}).get("step_s"),
            "z2_hwm_drop": zshard.get("zero2", {}).get("hwm_drop_vs_dp"),
            # flat on purpose (same perf_gate contract as above); the
            # rate suffixes hit _HIGHER_BETTER, the _ttft_s ones are
            # latency -> lower-better
            "serve_tok_s": serving.get("serve_tok_s"),
            "serve_p99_ttft_s": serving.get("serve_p99_ttft_s"),
            "serve_cb_speedup": serving.get("cb_tok_s_speedup"),
            "serve_beats_static": serving.get("cb_beats_static"),
            # flat on purpose (perf_gate): _speedup / _hit_frac /
            # _avoided_frac hit _HIGHER_BETTER's win-share overrides;
            # fastpath_p99_ttft_s stays lower-better via _s$
            "spec_tok_s_speedup": fastpath.get("spec_tok_s_speedup"),
            "prefix_hit_frac": fastpath.get("prefix_hit_frac"),
            "prefill_flops_avoided_frac": fastpath.get(
                "prefill_flops_avoided_frac"
            ),
            "fastpath_p99_ttft_s": fastpath.get("fastpath_p99_ttft_s"),
            # flat on purpose (perf_gate): _speedup$ makes the fleet
            # tok/s ratio higher-better; fleet_p99_ttft_s / handoff_s
            # are lower-better via _s$; dropped_req_total is the
            # hard-zero loss counter (_HARD_ZERO) — nonzero fails the
            # gate regardless of baseline
            "fleet_tok_s_speedup": fleet.get("fleet_tok_s_speedup"),
            "fleet_p99_ttft_s": fleet.get("fleet_p99_ttft_s"),
            "handoff_s": fleet.get("handoff_s"),
            "dropped_req_total": fleet.get("dropped_req_total"),
            # flat on purpose (perf_gate): the tracing rollup's
            # _share_frac / _decomp_err_frac row pins all three
            # lower-better
            "ttft_queue_share_frac": fleet.get("ttft_queue_share_frac"),
            "ttft_handoff_share_frac": fleet.get(
                "ttft_handoff_share_frac"
            ),
            "ttft_decomp_err_frac": fleet.get("ttft_decomp_err_frac"),
            # (fleet_beats_mono stays in extras.serving_fleet — the
            # headline only carries what perf_gate can gate, and the
            # 1.9KB tail budget is nearly full)
            # flat on purpose (perf_gate): tuned_step_s is lower-better
            # via _s$; tune_gain_frac is the autotuner's win over the
            # hand-picked default — HIGHER is better (_HIGHER_BETTER's
            # gain_frac$ override beats the _frac$ waste-share rule)
            "tuned_step_s": autotune.get("tuned_step_s"),
            "tune_gain_frac": autotune.get("tune_gain_frac"),
            "tuner_beats_default": autotune.get("tuner_beats_default"),
            "detail": "BENCH_DETAIL.json (full sections)",
        },
    }
    line = json.dumps(headline)
    assert len(line) < 1900, f"headline line {len(line)}B > 1.9KB tail budget"
    print(line)


if __name__ == "__main__":
    main()
