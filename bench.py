#!/usr/bin/env python
"""Benchmark harness: prints ONE JSON line with the headline metric.

Headline (BASELINE.md): ResNet-50 ImageNet-shape data-parallel training
throughput, img/s/chip, target >=70% of A100 NCCL-DDP per-chip throughput.
A100 DDP ResNet-50 (mixed precision, per-chip) is ~2500 img/s; vs_baseline
is measured against 0.7 * 2500 = 1750 img/s/chip.

Runs on however many chips are visible (the driver provides one real TPU
chip); DP sharding is exercised whenever device_count > 1.
"""

from __future__ import annotations

import json
import time

A100_DDP_RESNET50_IMG_S = 2500.0  # per-chip, AMP, the BASELINE §3 yardstick
TARGET_FRACTION = 0.70


def main() -> None:
    import jax
    import jax.numpy as jnp
    import numpy as np
    import optax

    import distributeddataparallel_tpu as ddp
    from distributeddataparallel_tpu.data.loader import shard_batch
    from distributeddataparallel_tpu.models.resnet import ResNet50
    from distributeddataparallel_tpu.ops import cross_entropy_loss

    mesh = ddp.make_mesh(("data",))
    n_dev = len(jax.devices())

    model = ResNet50(num_classes=1000, dtype=jnp.bfloat16)
    image_shape = (224, 224, 3)
    num_classes = 1000
    per_chip_batch = 128
    name = "resnet50_imagenet_dp"

    rng = jax.random.PRNGKey(0)
    sample = jnp.zeros((1,) + image_shape, jnp.float32)
    variables = model.init(rng, sample)
    params = variables["params"]
    model_state = {k: v for k, v in variables.items() if k != "params"}

    def loss_fn(params, ms, batch, rng):
        logits, new_vars = model.apply(
            {"params": params, **ms}, batch["image"], train=True,
            mutable=list(ms.keys()),
        )
        return cross_entropy_loss(logits, batch["label"]), ({}, new_vars)

    state = ddp.TrainState.create(
        apply_fn=model.apply,
        params=params,
        tx=optax.sgd(0.1, momentum=0.9),
        model_state=model_state,
    )
    state = ddp.broadcast_params(state, mesh)
    step = ddp.make_train_step(loss_fn, mesh=mesh, with_model_state=True)

    B = per_chip_batch * n_dev
    npr = np.random.default_rng(0)
    batch = {
        "image": npr.normal(size=(B,) + image_shape).astype(np.float32),
        "label": npr.integers(0, num_classes, size=(B,)).astype(np.int32),
    }
    batch = shard_batch(batch, mesh)
    key = jax.random.PRNGKey(1)

    # compile + warmup.  Fence by reading VALUES computed from the updated
    # params: that forces the whole step chain including the final
    # optimizer update.  (block_until_ready on donated params is NOT a
    # reliable fence on this runtime — donation aliasing can report the
    # buffer ready early, which once inflated this number ~35x; the last
    # step's loss alone would still exclude that step's backward/update.)
    def fence(state):
        leaf = jax.tree.leaves(state.params)[0]
        return float(jnp.sum(leaf.astype(jnp.float32)))

    for _ in range(4):
        state, metrics = step(state, batch, key)
    fence(state)

    iters = 20
    t0 = time.perf_counter()
    for _ in range(iters):
        state, metrics = step(state, batch, key)
    assert fence(state) == fence(state), "NaN params in benchmark"
    dt = time.perf_counter() - t0

    img_s = iters * B / dt
    img_s_chip = img_s / n_dev
    target = TARGET_FRACTION * A100_DDP_RESNET50_IMG_S
    print(
        json.dumps(
            {
                "metric": f"img/s/chip ({name})",
                "value": round(img_s_chip, 2),
                "unit": "img/s/chip",
                "vs_baseline": round(img_s_chip / target, 4),
            }
        )
    )


if __name__ == "__main__":
    main()
