#!/usr/bin/env python
"""Cross-run perf regression gate: compare a run against a baseline.

Usage:
    # Gate an events dir (run_summary extracted from its timeline):
    python scripts/perf_gate.py EVENTS_DIR --store runs/ --baseline main

    # Gate a bench headline file (BENCH_*.json `parsed.headline`):
    python scripts/perf_gate.py BENCH_r05.json --store runs/ --baseline bench

    # Promote the current run to be the named baseline:
    python scripts/perf_gate.py EVENTS_DIR --store runs/ --baseline main \
        --update-baseline

Exit codes: 0 = pass (or baseline updated), 1 = usage/IO error,
3 = regression.  A metric missing on either side is reported and
skipped ("missing"), never failed — a run that didn't enable --mfu
must not fail the MFU gate silently; it must say so.

RUN may be: an events directory (summary rebuilt from its merged
timeline), a run_summary JSON file, or a BENCH_*.json whose
``parsed.headline`` flat metrics are gated pairwise (direction inferred
from the metric name: bubble/step_s/bytes/overhead/us/restart metrics
are lower-better, everything else higher-better).

Every gated run is also appended to the store's ``index.jsonl``, so the
store accretes history whether or not the gate passes.

Import-light on purpose: stdlib + the stdlib-only observability
modules, never jax.
"""

from __future__ import annotations

import argparse
import json
import os
import re
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from distributeddataparallel_tpu.observability import baseline as bl  # noqa: E402
from distributeddataparallel_tpu.observability.events import (  # noqa: E402
    load_timeline,
)

REGRESS_EXIT = 3

#: Direction inference for bench-headline metric names: ONE ordered
#: (pattern, direction) table, first match wins, default "higher".
#: The ORDER carries the semantics — every earlier row exists to
#: override a later, broader one:
#:
#: 1. "higher" WIN suffixes first.  Throughput rates (tok_s, img_s,
#:    ..._per_s) and reclaimed_s (restart seconds the elastic resize
#:    path gave BACK — it ends in _s and contains "restart", but more
#:    of it is better) would otherwise hit row 3's ``_s$``/``restart``
#:    and gate backwards; the win-shares gain_frac (autotune speedup),
#:    _hit_frac (prefix-cache hit rate), _avoided_frac (prefill FLOPs
#:    skipped) and _speedup would be shadowed by row 3's ``_frac$``.
#: 2. "lower" TTFT-decomposition shares, pinned EXPLICITLY.  The
#:    tracing rollup's ``ttft_*_share_frac`` (queue/handoff seconds as
#:    a share of total TTFT) and ``ttft_decomp_err_frac`` (span-tree
#:    self-consistency error) are lower-better; today row 4's broad
#:    ``_frac$`` would catch them, but these gate the fleet smoke, and
#:    their direction must not silently flip if someone later widens
#:    row 1 with another ``..._frac`` win suffix (the ``gain_frac``
#:    shape is one keystroke away from ``share_frac``).
#: 3. "hard-zero" loss counters — the serving fleet's
#:    ``dropped_req_total`` shape (requests lost through an engine kill
#:    instead of drained-and-requeued).  A nonzero value fails the gate
#:    even when the baseline was just as bad: "no worse than a lossy
#:    baseline" is not a pass.  ``--allow-drops`` downgrades these to
#:    ordinary lower-better.  Must precede row 4, whose ``dropped``
#:    would claim them as merely lower-better.
#: 4. "lower" cost/waste names: time (step_s, _s/_us/_ms, latency),
#:    space (bytes), idle/waste shares (bubble, overhead, skew,
#:    _frac/_fraction), and failure-adjacent counts (restart, dropped).
#:
#: Anything unmatched defaults to "higher" (plain throughput/score
#: names).  tests/test_protocol_lint.py gates this table against every
#: headline metric the bench scripts actually emit.
_DIRECTION_TABLE: tuple[tuple[re.Pattern, str], ...] = (
    (re.compile(r"(tok_s|img_s|_per_s|reclaimed_s|gain_frac|_hit_frac"
                r"|_avoided_frac|_speedup)$"), "higher"),
    (re.compile(r"(_share_frac|_decomp_err_frac)$"), "lower"),
    (re.compile(r"dropped(_[a-z0-9]+)*_total$"), "hard-zero"),
    (re.compile(r"(bubble|step_s|_s$|bytes|overhead|_us$|_ms$|restart"
                r"|latency|skew|dropped|_frac$|_fraction$)"), "lower"),
)
_DEFAULT_DIRECTION = "higher"


def _bench_direction(name: str) -> str:
    """'higher' | 'lower' | 'hard-zero' for a headline metric name."""
    for pattern, direction in _DIRECTION_TABLE:
        if pattern.search(name):
            return direction
    return _DEFAULT_DIRECTION


def load_run(path: str) -> tuple[dict, str]:
    """RUN argument -> (flat metric dict, source label)."""
    if os.path.isdir(path):
        records = load_timeline(path)
        if not records:
            raise ValueError(f"no event records under {path}")
        return bl.run_summary_from_timeline(records), "events"
    with open(path) as fh:
        data = json.load(fh)
    headline = data.get("parsed", {}).get("headline") or data.get("headline")
    if isinstance(headline, dict):
        flat = {k: v for k, v in headline.items()
                if isinstance(v, (int, float)) and not isinstance(v, bool)}
        if not flat:
            raise ValueError(f"{path}: headline has no numeric metrics")
        return flat, "bench"
    if not isinstance(data, dict):
        raise ValueError(f"{path}: not a JSON object")
    return data, "summary"


def gate_metrics_for(summary: dict, source: str,
                     default_tol: float) -> dict[str, tuple[str, float]]:
    """The metric set to gate: the fixed GATE_METRICS for trainer
    summaries, or every shared numeric headline for bench files (with
    name-inferred direction)."""
    if source != "bench":
        return bl.GATE_METRICS
    # hard-zero metrics still gate pairwise as lower-better here; the
    # absolute value>0 check is main()'s post-pass over the same table
    return {
        name: (
            {"hard-zero": "lower"}.get(
                _bench_direction(name), _bench_direction(name)
            ),
            default_tol,
        )
        for name in sorted(summary)
    }


def main(argv: list[str] | None = None) -> int:
    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.add_argument("run", help="events dir, run_summary JSON, or "
                                "BENCH_*.json")
    ap.add_argument("--store", required=True,
                    help="runs store directory (index.jsonl + baselines/)")
    ap.add_argument("--baseline", required=True,
                    help="baseline name to gate against / update")
    ap.add_argument("--threshold", action="append", default=[],
                    metavar="METRIC=FRAC",
                    help="per-metric relative tolerance override "
                         "(repeatable), e.g. --threshold mfu_mean=0.02")
    ap.add_argument("--default-threshold", type=float, default=0.05,
                    help="tolerance for bench-headline metrics "
                         "(default 0.05)")
    ap.add_argument("--allow-drops", action="store_true",
                    help="gate dropped_*_total metrics as ordinary "
                         "lower-better instead of hard-zero")
    ap.add_argument("--update-baseline", action="store_true",
                    help="record this run as the named baseline instead "
                         "of gating")
    ap.add_argument("--json", action="store_true",
                    help="emit the comparison as JSON")
    args = ap.parse_args(argv)

    thresholds = {}
    for spec in args.threshold:
        name, sep, frac = spec.partition("=")
        if not sep:
            print(f"perf_gate: bad --threshold {spec!r} (want METRIC=FRAC)",
                  file=sys.stderr)
            return 1
        try:
            thresholds[name] = float(frac)
        except ValueError:
            print(f"perf_gate: bad --threshold value {frac!r}",
                  file=sys.stderr)
            return 1

    try:
        summary, source = load_run(args.run)
    except (OSError, ValueError, json.JSONDecodeError) as exc:
        print(f"perf_gate: cannot load run {args.run!r}: {exc}",
              file=sys.stderr)
        return 1

    bl.append_run(args.store, summary, name=args.baseline, source=source)

    if args.update_baseline:
        path = bl.save_baseline(args.store, args.baseline, summary)
        print(f"perf_gate: baseline {args.baseline!r} updated -> {path}")
        return 0

    base = bl.load_baseline(args.store, args.baseline)
    if base is None:
        print(f"perf_gate: no baseline {args.baseline!r} in {args.store}; "
              f"record one with --update-baseline", file=sys.stderr)
        return 1

    result = bl.compare_to_baseline(
        summary, base, thresholds=thresholds,
        metrics=gate_metrics_for(summary, source, args.default_threshold),
    )
    if not args.allow_drops:
        for name in sorted(summary):
            value = summary[name]
            if not (_bench_direction(name) == "hard-zero"
                    and isinstance(value, (int, float))
                    and not isinstance(value, bool) and value > 0):
                continue
            if name not in result["regressed"]:
                result["regressed"].append(name)
            result["ok"] = False
            result["checks"] = [
                c for c in result["checks"] if c["metric"] != name
            ] + [{
                "metric": name, "status": "regress", "value": value,
                "baseline": base.get(name, 0.0), "bound": 0.0,
                "direction": "hard-zero",
            }]
    # GL002 attribution: the fingerprint is an identity, not a gated
    # metric (compare_metric treats non-numerics as missing), so it gets
    # explicit handling — same graph means a regression is environment
    # drift; a different graph means the program itself changed.
    run_fp = summary.get("collective_fp")
    base_fp = base.get("collective_fp")
    attribution = None
    if run_fp and base_fp:
        attribution = (
            "collective graph unchanged vs baseline "
            f"(fp {run_fp}) — any regression is environment drift"
            if run_fp == base_fp else
            f"collective graph CHANGED vs baseline (fp {run_fp} != "
            f"{base_fp}) — a regression is attributable to the step's "
            "collective structure"
        )
        result["collective_fp"] = {
            "run": run_fp, "baseline": base_fp,
            "changed": run_fp != base_fp,
        }
    if args.json:
        print(json.dumps(result, indent=2))
    else:
        for c in result["checks"]:
            mark = {"pass": "ok", "regress": "REGRESS",
                    "missing": "missing"}[c["status"]]
            if c["status"] == "missing":
                print(f"  {c['metric']:<18} {mark:>8}  "
                      f"(run={c['value']!r} baseline={c['baseline']!r})")
            else:
                print(f"  {c['metric']:<18} {mark:>8}  "
                      f"run={c['value']:.6g} baseline={c['baseline']:.6g} "
                      f"bound={c['bound']:.6g} ({c['direction']})")
    if attribution and not args.json:
        print(f"  {attribution}")
    if not result["ok"]:
        print(f"perf_gate: REGRESSION vs {args.baseline!r}: "
              + ", ".join(result["regressed"]), file=sys.stderr)
        return REGRESS_EXIT
    note = (f" ({len(result['missing'])} metric(s) missing, skipped)"
            if result["missing"] else "")
    print(f"perf_gate: pass vs {args.baseline!r}{note}")
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
