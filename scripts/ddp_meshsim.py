#!/usr/bin/env python
"""Compile-only mesh simulation at scales this box doesn't have.

Usage:
    # Lower + lint + size gpt2-small dp on a fake 64-device mesh:
    python scripts/ddp_meshsim.py --model gpt2-small --mode dp --devices 64

    # Sweep device counts and store records for perf_gate to diff:
    python scripts/ddp_meshsim.py --model gpt2-small --devices 8,64,256 \
        --store runs/

    # CI smoke (cnn + gpt2-small, dp, 8 and 32 devices):
    python scripts/ddp_meshsim.py --check

Each device count needs its own process: jax fixes the device set at
import time, so the orchestrator (this script, which never imports jax)
re-invokes itself per count with
``XLA_FLAGS=--xla_force_host_platform_device_count=N`` already in the
child's environment.  The child runs ``analysis.mesh_sim.simulate`` —
AOT lowering, shard-flow lint (SF2xx), schedule lint (SL3xx), and the
compiler's per-device ``memory_analysis()`` — and prints one JSON
record on stdout.

``--store`` appends each record to a baseline store index
(``observability.baseline.append_run``) named by its simulation
fingerprint; the record's flat ``headline`` byte metrics make
``scripts/perf_gate.py`` treat it as a bench file, so predicted
per-chip footprints are gated across commits like any measured metric.

Exit codes: 0 = clean, 1 = usage/subprocess error, 2 = lint findings
or a config predicted not to fit.
"""

from __future__ import annotations

import argparse
import json
import os
import subprocess
import sys

ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, ROOT)

FINDINGS_EXIT = 2

#: --check preset: enough to catch a broken lowering or a lint
#: regression on both a conv net and the transformer path — including
#: the sharded-update variants (zero2: reduce-scatter manifest + IR;
#: zero3: params resident as a flat shard, gather-per-bucket IR) and
#: the zero-bubble pipeline (B/W-split scans + zb schedule IR through
#: SL301-SL304) — small enough to stay in CI budget
CHECK_CASES = (
    "cnn:dp", "gpt2-small:dp", "gpt2-small:zero2", "gpt2-small:zero3",
    "gpt2-small:pp_zb",
)
CHECK_DEVICES = (8, 32)


def worker_main(args) -> int:
    """Child-process entry: device count already forced via XLA_FLAGS
    by the parent, so importing jax here sees the fake mesh."""
    from distributeddataparallel_tpu.analysis.mesh_sim import simulate

    record = simulate(
        args.model,
        args.mode,
        batch_per_chip=args.batch_per_chip,
        seq=args.seq,
        pp_stages=args.pp_stages,
        do_compile=not args.no_compile,
        hbm_budget_bytes=args.hbm_budget_bytes or None,
    )
    json.dump(record, sys.stdout)
    sys.stdout.write("\n")
    return 0


def spawn_case(devices: int, argv_tail: list[str]) -> dict:
    """Run one (model, mode, devices) case in a fresh process and
    parse its record.  Raises RuntimeError with the child's stderr on
    failure."""
    env = dict(os.environ)
    env["JAX_PLATFORMS"] = "cpu"
    env["XLA_FLAGS"] = (
        env.get("XLA_FLAGS", "")
        + f" --xla_force_host_platform_device_count={devices}"
    ).strip()
    proc = subprocess.run(
        [sys.executable, os.path.abspath(__file__), "--worker", *argv_tail],
        env=env, capture_output=True, text=True,
    )
    if proc.returncode != 0:
        raise RuntimeError(
            f"simulation subprocess failed (devices={devices}):\n"
            + proc.stderr.strip()[-2000:]
        )
    # the record is the last stdout line; anything before it is jax noise
    line = proc.stdout.strip().splitlines()[-1]
    return json.loads(line)


def summarize(record: dict) -> str:
    fit = record.get("fit")
    mem = "" if not fit else (
        f"  required={fit['required_bytes'] / 2**30:.2f}GiB"
        f" budget={fit['budget_bytes'] / 2**30:.0f}GiB"
        f" {'FITS' if fit['fits'] else 'DOES NOT FIT'}"
    )
    n_f = len(record["findings"])
    lint = "clean" if not n_f else f"{n_f} finding(s)"
    return (
        f"{record['model']}:{record['mode']} @ {record['devices']}dev"
        f" params={record['params_m']}M  lint={lint}{mem}"
    )


def record_failed(record: dict) -> bool:
    fit = record.get("fit")
    return bool(record["findings"]) or bool(fit and not fit["fits"])


def store_record(store: str, record: dict) -> None:
    from distributeddataparallel_tpu.analysis.mesh_sim import fingerprint
    from distributeddataparallel_tpu.observability import baseline as bl

    bl.append_run(store, record, name=fingerprint(record), source="meshsim")


def build_parser() -> argparse.ArgumentParser:
    p = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    p.add_argument("--model", default="gpt2-small",
                   help="cnn | mlp | tiny-lm | gpt2-small")
    p.add_argument("--mode", default="dp",
                   help="dp | zero | zero2 | zero3 | fsdp | pp | pp_zb "
                        "| all (all = every mode the model supports)")
    p.add_argument("--devices", default="8",
                   help="comma-separated fake device counts (one "
                        "subprocess each)")
    p.add_argument("--batch-per-chip", type=int, default=2)
    p.add_argument("--seq", type=int, default=128)
    p.add_argument("--pp-stages", type=int, default=4)
    p.add_argument("--hbm-budget-bytes", type=int, default=0,
                   help="per-chip budget override (default: detected "
                        "or 32GiB)")
    p.add_argument("--no-compile", action="store_true",
                   help="lower + lint only, skip compile and the "
                        "memory-fit prediction")
    p.add_argument("--store", metavar="DIR",
                   help="append each record to this baseline store")
    p.add_argument("--json", action="store_true",
                   help="print full records as JSON lines instead of "
                        "summaries")
    p.add_argument("--check", action="store_true",
                   help="CI smoke: cnn + gpt2-small, dp, 8 and 32 "
                        "devices; nonzero on any finding")
    p.add_argument("--worker", action="store_true", help=argparse.SUPPRESS)
    return p


def case_argv(args, model: str, mode: str) -> list[str]:
    tail = [
        "--model", model, "--mode", mode,
        "--batch-per-chip", str(args.batch_per_chip),
        "--seq", str(args.seq),
        "--pp-stages", str(args.pp_stages),
        "--hbm-budget-bytes", str(args.hbm_budget_bytes),
    ]
    if args.no_compile:
        tail.append("--no-compile")
    return tail


def main(argv: list[str] | None = None) -> int:
    args = build_parser().parse_args(argv)
    if args.worker:
        return worker_main(args)

    if args.check:
        cases = [tuple(c.split(":")) for c in CHECK_CASES]
        devices = list(CHECK_DEVICES)
    else:
        if args.mode == "all":
            # fsdp/pp lower transformers only; the sharded-update
            # family (dp/zero*) lowers everything
            modes = ["dp", "zero", "zero2", "zero3"]
            if args.model not in ("cnn", "mlp"):
                modes += ["fsdp", "pp", "pp_zb"]
            cases = [(args.model, m) for m in modes]
        else:
            cases = [(args.model, args.mode)]
        try:
            devices = [int(d) for d in args.devices.split(",") if d]
        except ValueError:
            print(f"ddp_meshsim: bad --devices {args.devices!r}",
                  file=sys.stderr)
            return 1
        if not devices:
            print("ddp_meshsim: no device counts given", file=sys.stderr)
            return 1

    failed = False
    for model, mode in cases:
        for n in devices:
            try:
                record = spawn_case(n, case_argv(args, model, mode))
            except RuntimeError as exc:
                print(f"ddp_meshsim: {exc}", file=sys.stderr)
                return 1
            if args.json:
                print(json.dumps(record))
            else:
                print(summarize(record))
                for f in record["findings"]:
                    print(f"    {f}")
            if args.store:
                store_record(args.store, record)
            failed = failed or record_failed(record)

    return FINDINGS_EXIT if failed else 0


if __name__ == "__main__":
    sys.exit(main())
