#!/usr/bin/env python
"""Serving CLI: continuous-batching engine under Poisson open-loop load.

Usage:
    python scripts/ddp_serve.py --model tiny --rate 20 --duration 2 \
        --events-dir runs/serve
    python scripts/ddp_serve.py --smoke          # CI: tiny burst, asserts
    python scripts/ddp_serve.py --model gpt2_124m --seq-len 256 \
        --slots 8 --rate 4 --duration 5 --store .aot-cache

Builds the model with randomly-initialized params (the traffic is
synthetic token ids — serving-path performance and correctness do not
depend on trained weights), wires the engine to an events dir +
metrics registry, replays a seeded loadgen trace, and prints the
serving summary as JSON.  The events dir afterwards holds a mergeable
timeline that ``ddp_trace.py`` exports to Perfetto (request spans,
active-slot counter) and ``ddp_report.py`` renders with its Serving
section.

``--smoke`` is the CI gate: tiny model, ~2s virtual burst, asserting
at least one completed request and a structurally valid trace export.
``--virtual-dt`` makes any run deterministic (the clock advances per
engine step instead of reading the host clock).
"""

from __future__ import annotations

import argparse
import json
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def _ensure_cpu() -> None:
    """CPU-safe defaults when no accelerator is configured (same
    contract as ddplint: must run before the first jax import)."""
    if "jax" in sys.modules:
        return
    os.environ.setdefault("JAX_PLATFORMS", "cpu")
    flags = os.environ.get("XLA_FLAGS", "")
    if "xla_force_host_platform_device_count" not in flags:
        os.environ["XLA_FLAGS"] = (
            flags + " --xla_force_host_platform_device_count=8"
        ).strip()


def build_parser() -> argparse.ArgumentParser:
    ap = argparse.ArgumentParser(description=__doc__.split("\n")[0])
    ap.add_argument("--model", default="tiny",
                    choices=("tiny", "gpt2_124m"))
    ap.add_argument("--seq-len", type=int, default=None,
                    help="override max_seq_len (default: model's)")
    ap.add_argument("--slots", type=int, default=8)
    ap.add_argument("--blocks", type=int, default=64)
    ap.add_argument("--block-size", type=int, default=16)
    ap.add_argument("--chunk", type=int, default=32,
                    help="prefill chunk tokens")
    ap.add_argument("--max-prefill-chunks", type=int, default=1)
    ap.add_argument("--rate", type=float, default=8.0,
                    help="Poisson arrival rate, requests/s")
    ap.add_argument("--duration", type=float, default=2.0)
    ap.add_argument("--prompt-len", default="4,24",
                    help="uniform prompt length range 'lo,hi'")
    ap.add_argument("--output-len", default="4,16",
                    help="uniform output length range 'lo,hi'")
    ap.add_argument("--seed", type=int, default=0)
    ap.add_argument("--quantize-kv", action="store_true")
    ap.add_argument("--quantize-weights", action="store_true")
    ap.add_argument("--prefix-cache", action="store_true",
                    help="radix prefix cache: share KV blocks across "
                         "requests with a common prompt prefix")
    ap.add_argument("--spec-k", type=int, default=0,
                    help="speculative decoding: n-gram-draft k tokens "
                         "per step through the verify program (0 = off)")
    ap.add_argument("--spec-ngram", type=int, default=3,
                    help="longest n-gram the self-draft proposer matches")
    ap.add_argument("--prefix-pool", type=int, default=0,
                    help="shared-prefix trace mode: pool of N fixed "
                         "prefixes sampled with Zipf rank weights")
    ap.add_argument("--prefix-len", type=int, default=0,
                    help="tokens per pooled shared prefix")
    ap.add_argument("--zipf-alpha", type=float, default=1.1,
                    help="Zipf exponent over the prefix pool ranks")
    ap.add_argument("--turns", type=int, default=1,
                    help="multi-turn sessions: each base request seeds "
                         "a session with N turns (follow-up prompts "
                         "extend the prior turn's)")
    ap.add_argument("--turn-gap", type=float, default=0.25,
                    help="mean seconds between a session's turns")
    ap.add_argument("--fleet", default=None, metavar="P:D",
                    help="disaggregated fleet: P prefill + D decode "
                         "engine processes behind the session-affinity "
                         "router, KV handoff over TCP")
    ap.add_argument("--kill-engine", default=None, metavar="NAME|auto",
                    help="fleet fault injection: terminate this engine "
                         "worker mid-run ('auto' picks a decode engine)")
    ap.add_argument("--kill-after", type=float, default=None,
                    help="seconds into the drive to kill (default: 60%% "
                         "through the arrival trace)")
    ap.add_argument("--events-dir", default=None)
    ap.add_argument("--store", default=None,
                    help="ExecutableStore dir (warm-start AOT reuse)")
    ap.add_argument("--virtual-dt", type=float, default=None,
                    help="deterministic mode: seconds per engine step")
    ap.add_argument("--smoke", action="store_true",
                    help="CI smoke: tiny burst + trace validity asserts")
    return ap


def _range(spec: str) -> tuple[int, int]:
    lo, hi = (int(x) for x in spec.split(","))
    return lo, hi


def _run_fleet(args) -> int:
    """``--fleet P:D``: spawn the disaggregated tiers as worker
    processes under the launcher, drive a (multi-turn) loadgen trace
    through the router, and — under ``--smoke`` — assert the fleet
    contract: every request completes (zero dropped, even through an
    injected engine kill), at least one KV handoff crossed tiers, at
    least one follow-up was affinity-routed, the merged timeline stays
    schema- and trace-valid, every span tree is lineage-clean across
    the three process boundaries (router, prefill, decode), every
    completed request's TTFT decomposition reproduces its measured
    TTFT within 5%, and the mid-run /metrics scrape of every live
    process parsed and carried the required series."""
    from distributeddataparallel_tpu.models.transformer import (
        gpt2_124m,
        tiny_lm,
    )
    from distributeddataparallel_tpu.serving import (
        EngineConfig,
        LoadConfig,
        make_trace,
    )
    from distributeddataparallel_tpu.serving.fleet import (
        FleetConfig,
        FleetService,
    )

    try:
        n_prefill, n_decode = (int(x) for x in args.fleet.split(":"))
    except ValueError:
        print(f"ddp_serve: bad --fleet {args.fleet!r} (want P:D)",
              file=sys.stderr)
        return 1

    if args.smoke:
        args.model = "tiny"
        args.duration = min(args.duration, 1.5)
        args.rate = min(args.rate, 6.0)
        args.turns = max(args.turns, 2)
        # Affinity keys hash the first KV block: keep prompts at least
        # one block long so a follow-up's key matches its base turn's.
        args.prompt_len = "20,40"
        args.output_len = "6,12"
        if args.kill_engine is None:
            args.kill_engine = "auto"

    vocab = (gpt2_124m() if args.model == "gpt2_124m"
             else tiny_lm()).vocab_size
    trace = make_trace(LoadConfig(
        rate_rps=args.rate,
        duration_s=args.duration,
        prompt_len=_range(args.prompt_len),
        output_len=_range(args.output_len),
        vocab_size=vocab,
        seed=args.seed,
        prefix_pool=args.prefix_pool,
        prefix_len=args.prefix_len,
        zipf_alpha=args.zipf_alpha,
        turns=args.turns,
        turn_gap_s=args.turn_gap,
    ))
    kill_after = None
    kill_name = None
    if args.kill_engine:
        last_arrival = trace[-1]["arrival_s"] if trace else 0.0
        kill_after = (args.kill_after if args.kill_after is not None
                      else 0.6 * last_arrival)
        kill_name = (None if args.kill_engine == "auto"
                     else args.kill_engine)
    svc = FleetService(
        model=args.model,
        seq_len=args.seq_len,
        seed=args.seed,
        engine_config=EngineConfig(
            num_slots=args.slots,
            num_blocks=args.blocks,
            block_size=args.block_size,
            prefill_chunk=args.chunk,
            max_prefill_chunks_per_step=args.max_prefill_chunks,
            quantized_kv=args.quantize_kv,
            quantize_weights=args.quantize_weights,
            store_dir=args.store,
            # Affinity hits only pay off if the home decode engine's
            # prefix cache actually holds the session's blocks.
            prefix_cache=True,
            spec_k=args.spec_k,
            spec_ngram=args.spec_ngram,
        ),
        fleet_config=FleetConfig(prefill=n_prefill, decode=n_decode),
        events_dir=args.events_dir,
        kill_after_s=kill_after,
        kill_engine=kill_name,
    )
    out = svc.run(trace)
    out["fleet"] = f"{n_prefill}:{n_decode}"
    print(json.dumps(out, indent=1, sort_keys=True, default=str))

    if not args.smoke:
        return 0
    failures = []
    if out["completed"] < len(trace):
        failures.append(
            f"fleet smoke: only {out['completed']}/{len(trace)} "
            "requests completed"
        )
    if out["dropped_req_total"] != 0:
        failures.append(
            f"fleet smoke: {out['dropped_req_total']} dropped requests "
            "(engine-kill drain must requeue, not lose)"
        )
    if out["handoffs"] < 1:
        failures.append("fleet smoke: no prefill->decode KV handoff")
    if args.kill_engine and out["kills"] < 1:
        failures.append("fleet smoke: engine kill did not fire")
    if args.events_dir:
        from distributeddataparallel_tpu.observability.events import (
            load_timeline,
        )
        from distributeddataparallel_tpu.observability.schema import (
            validate_file,
        )
        from distributeddataparallel_tpu.observability.trace_export import (
            to_trace_events,
            validate_trace,
        )

        problems = validate_file(
            os.path.join(args.events_dir, "timeline.jsonl")
        )
        failures.extend(problems[:5])
        records = load_timeline(args.events_dir)
        failures.extend(validate_trace(to_trace_events(records))[:5])
        kinds = {r.get("kind") for r in records}
        needed = ["route_admit", "kv_handoff", "tier_summary"]
        if args.kill_engine:
            needed.append("engine_verdict")
        for kind in needed:
            if kind not in kinds:
                failures.append(f"fleet smoke: no {kind} event")
        if not any(r.get("kind") == "route_admit" and r.get("affinity")
                   for r in records):
            failures.append(
                "fleet smoke: no affinity-routed follow-up turn"
            )
        # Distributed tracing: span trees must survive three process
        # boundaries (router -> prefill -> decode) with zero orphans,
        # and each request's critical-path decomposition must account
        # for its measured TTFT.
        from distributeddataparallel_tpu.observability.critical_path import (
            check_lineage,
            request_decompositions,
            ttft_rollup,
        )

        failures.extend(
            f"fleet smoke: {p}" for p in check_lineage(records)[:5]
        )
        decomps = request_decompositions(records)
        if len(decomps) < out["completed"]:
            failures.append(
                f"fleet smoke: TTFT decomposition covers only "
                f"{len(decomps)}/{out['completed']} completed requests"
            )
        bad = [d for d in decomps if d["err_frac"] > 0.05]
        if bad:
            failures.append(
                f"fleet smoke: {len(bad)} request(s) decompose to "
                "more than 5% off their measured TTFT (worst "
                f"{max(d['err_frac'] for d in bad):.1%}, "
                f"req {max(bad, key=lambda d: d['err_frac'])['req']})"
            )
        out["ttft_decomp"] = ttft_rollup(decomps)
    # Live /metrics plane: the service scraped every live endpoint
    # mid-run (at the first completion, while requests were still
    # outstanding); each payload must have parsed and carried the
    # series the monitor renders.
    scraped = out.get("metrics_scrape") or {}
    router_series = scraped.get("router")
    if not isinstance(router_series, dict) or "_error" in router_series:
        failures.append(
            "fleet smoke: router /metrics scrape failed "
            f"({(router_series or {}).get('_error', 'never scraped')})"
        )
    else:
        for name in ("router_queue_depth",
                     "fleet_prefill_p50_ttft_s",
                     "fleet_prefill_p99_ttft_s",
                     "fleet_decode_p50_ttft_s",
                     "fleet_decode_p99_ttft_s"):
            if name not in router_series:
                failures.append(
                    f"fleet smoke: router /metrics missing {name}"
                )
    workers = {k: v for k, v in scraped.items() if k != "router"}
    if not workers:
        failures.append("fleet smoke: no engine /metrics endpoint scraped")
    for wname, series in sorted(workers.items()):
        if not isinstance(series, dict) or "_error" in series:
            failures.append(
                f"fleet smoke: engine {wname} /metrics scrape failed "
                f"({(series or {}).get('_error', 'bad payload')})"
            )
        elif "serve_tok_s" not in series:
            failures.append(
                f"fleet smoke: engine {wname} /metrics missing "
                "serve_tok_s"
            )
    if failures:
        print("SMOKE FAIL:\n  " + "\n  ".join(failures), file=sys.stderr)
        return 1
    roll = out.get("ttft_decomp") or {}
    decomp_note = (
        f", ttft queue_share={roll['ttft_queue_share_frac']:.2f} "
        f"decomp_err={roll['ttft_decomp_err_frac']:.3f} "
        f"over {roll['requests']} traced request(s)"
        if roll.get("requests") else ""
    )
    print("fleet smoke OK: "
          f"{out['completed']}/{len(trace)} requests, "
          f"{out['handoffs']} handoffs, {out['requeued']} requeued "
          f"through {out['kills']} kill(s), "
          f"p99_ttft={out.get('serve_p99_ttft_s', 0):.3f}s"
          f"{decomp_note}")
    return 0


def main(argv=None) -> int:
    args = build_parser().parse_args(argv)
    _ensure_cpu()

    if args.fleet:
        return _run_fleet(args)

    import jax
    import jax.numpy as jnp

    from distributeddataparallel_tpu.models import TransformerLM
    from distributeddataparallel_tpu.models.transformer import (
        gpt2_124m,
        tiny_lm,
    )
    from distributeddataparallel_tpu.observability.events import (
        EventLog,
        events_path,
        merge_timeline,
    )
    from distributeddataparallel_tpu.observability.registry import (
        MetricsRegistry,
    )
    from distributeddataparallel_tpu.serving import (
        EngineConfig,
        InferenceEngine,
        LoadConfig,
        VirtualClock,
        kv_pool_bytes,
        make_trace,
        run_load,
    )

    if args.smoke:
        args.model = "tiny"
        args.virtual_dt = args.virtual_dt or 0.005
        args.duration = min(args.duration, 2.0)

    if args.model == "gpt2_124m":
        cfg = gpt2_124m(max_seq_len=args.seq_len or 256,
                        dtype=jnp.bfloat16)
    else:
        cfg = tiny_lm(max_seq_len=args.seq_len or 128)
    model = TransformerLM(cfg)
    params = model.init(
        jax.random.PRNGKey(args.seed),
        jnp.zeros((1, 4), jnp.int32),
    )["params"]

    events = None
    if args.events_dir:
        os.makedirs(args.events_dir, exist_ok=True)
        events = EventLog(events_path(args.events_dir, 0), 0)
        events.emit("run_start", argv=sys.argv[1:], role="serve")
    registry = MetricsRegistry()

    clock = VirtualClock(args.virtual_dt) if args.virtual_dt else None
    ecfg = EngineConfig(
        num_slots=args.slots,
        num_blocks=args.blocks,
        block_size=args.block_size,
        prefill_chunk=args.chunk,
        max_prefill_chunks_per_step=args.max_prefill_chunks,
        quantized_kv=args.quantize_kv,
        quantize_weights=args.quantize_weights,
        store_dir=args.store,
        prefix_cache=args.prefix_cache,
        spec_k=args.spec_k,
        spec_ngram=args.spec_ngram,
    )
    engine = InferenceEngine(
        model, params, ecfg, events=events, registry=registry,
        **({"time_fn": clock} if clock else {}),
    )
    trace = make_trace(LoadConfig(
        rate_rps=args.rate,
        duration_s=args.duration,
        prompt_len=_range(args.prompt_len),
        output_len=_range(args.output_len),
        vocab_size=cfg.vocab_size,
        seed=args.seed,
        prefix_pool=args.prefix_pool,
        prefix_len=args.prefix_len,
        zipf_alpha=args.zipf_alpha,
        turns=args.turns,
        turn_gap_s=args.turn_gap,
    ))
    out = run_load(engine, trace, clock=clock)
    out["requests"] = len(trace)
    out["kv_pool_bytes"] = kv_pool_bytes(
        cfg, args.blocks, args.block_size, quantized_kv=args.quantize_kv
    )
    if getattr(engine, "warm_report", None):
        out["warm_start"] = engine.warm_report

    if events is not None:
        events.emit("metrics", snapshot=registry.snapshot())
        events.emit("run_end", status="ok")
        events.close()
        merge_timeline(args.events_dir)

    print(json.dumps(out, indent=1, sort_keys=True, default=str))

    if args.smoke:
        from distributeddataparallel_tpu.observability.trace_export import (
            to_trace_events,
            validate_trace,
        )

        failures = []
        if out["completed"] < 1:
            failures.append("smoke: no request completed")
        if args.events_dir:
            from distributeddataparallel_tpu.observability.events import (
                load_timeline,
            )
            from distributeddataparallel_tpu.observability.schema import (
                validate_file,
            )

            problems = validate_file(
                os.path.join(args.events_dir, "timeline.jsonl")
            )
            failures.extend(problems[:5])
            records = load_timeline(args.events_dir)
            trace_problems = validate_trace(to_trace_events(records))
            failures.extend(trace_problems[:5])
            kinds = {r.get("kind") for r in records}
            for needed in ("request_admit", "decode_step",
                           "request_done"):
                if needed not in kinds:
                    failures.append(f"smoke: no {needed} event")
            # Standalone engine derives its own root span per request;
            # the resulting trees must still be lineage-clean.
            from distributeddataparallel_tpu.observability.critical_path import (  # noqa: E501
                check_lineage,
            )

            failures.extend(
                f"smoke: {p}" for p in check_lineage(records)[:5]
            )

        # Phase 2: the serving fast path — prefix cache + speculative
        # decoding on a shared-prefix Zipf trace.  Gates that the radix
        # cache actually hits, the verifier actually accepts drafts,
        # and that the new prefix_hit / spec_verify kinds keep the
        # timeline and the Perfetto export schema-valid.
        fp_dir = None
        fp_events = None
        if args.events_dir:
            fp_dir = os.path.join(args.events_dir, "fastpath")
            os.makedirs(fp_dir, exist_ok=True)
            fp_events = EventLog(events_path(fp_dir, 0), 0)
            fp_events.emit("run_start", argv=["--smoke", "fastpath"],
                           role="serve")
        fp_clock = VirtualClock(args.virtual_dt)
        fp_engine = InferenceEngine(
            model, params,
            EngineConfig(
                num_slots=args.slots,
                num_blocks=args.blocks,
                block_size=args.block_size,
                prefill_chunk=args.chunk,
                max_prefill_chunks_per_step=args.max_prefill_chunks,
                quantized_kv=args.quantize_kv,
                quantize_weights=args.quantize_weights,
                store_dir=args.store,
                prefix_cache=True,
                spec_k=max(args.spec_k, 3),
                spec_ngram=args.spec_ngram,
            ),
            events=fp_events, time_fn=fp_clock,
        )
        fp_trace = make_trace(LoadConfig(
            rate_rps=24.0,
            duration_s=args.duration,
            prompt_len=(56, 72),
            output_len=(8, 16),
            vocab_size=cfg.vocab_size,
            seed=args.seed,
            prefix_pool=4,
            prefix_len=48,
            zipf_alpha=args.zipf_alpha,
        ))
        fp_out = run_load(fp_engine, fp_trace, clock=fp_clock)
        if fp_events is not None:
            fp_events.emit("run_end", status="ok")
            fp_events.close()
            merge_timeline(fp_dir)
        if fp_out["completed"] < len(fp_trace):
            failures.append(
                "smoke fastpath: only "
                f"{fp_out['completed']}/{len(fp_trace)} completed"
            )
        if fp_engine.prefix_hits < 1:
            failures.append("smoke fastpath: no prefix-cache hit")
        accept_mean = fp_out.get("spec_accept_mean", 0.0)
        if accept_mean <= 1.0:
            failures.append(
                "smoke fastpath: spec_accept_mean "
                f"{accept_mean:.2f} <= 1 (speculation not landing)"
            )
        if fp_dir is not None:
            problems = validate_file(
                os.path.join(fp_dir, "timeline.jsonl")
            )
            failures.extend(problems[:5])
            records = load_timeline(fp_dir)
            trace_problems = validate_trace(to_trace_events(records))
            failures.extend(trace_problems[:5])
            kinds = {r.get("kind") for r in records}
            for needed in ("prefix_hit", "spec_verify"):
                if needed not in kinds:
                    failures.append(f"smoke fastpath: no {needed} event")

        if failures:
            print("SMOKE FAIL:\n  " + "\n  ".join(failures),
                  file=sys.stderr)
            return 1
        print("serving smoke OK: "
              f"{out['completed']}/{out['requests']} requests, "
              f"{out.get('serve_tok_s', 0):.1f} tok/s; fastpath "
              f"hit_frac={fp_out.get('prefix_hit_frac', 0):.2f} "
              f"accept_mean={accept_mean:.2f}")
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
