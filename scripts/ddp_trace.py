#!/usr/bin/env python
"""Export a run's gang timeline as Chrome/Perfetto trace JSON.

Usage:
    python scripts/ddp_trace.py EVENTS_DIR                 # -> EVENTS_DIR/trace.json
    python scripts/ddp_trace.py EVENTS_DIR -o run.trace.json
    python scripts/ddp_trace.py EVENTS_DIR --check         # validate only

Merges the per-worker event files into ``timeline.jsonl`` first when
the run died before its exit-time merge, then converts it with
``observability.trace_export``: one track per rank plus the supervisor,
spans as complete events, mfu/step_s/memory counter tracks, and
nan_skip/restart/alert incidents as instant marks.  Open the output at
https://ui.perfetto.dev (or chrome://tracing).

Import-light on purpose: stdlib + the stdlib-only observability
modules, never jax.
"""

from __future__ import annotations

import argparse
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from distributeddataparallel_tpu.observability.events import (  # noqa: E402
    load_timeline,
)
from distributeddataparallel_tpu.observability.trace_export import (  # noqa: E402
    to_trace_events,
    validate_trace,
    write_trace,
)


def main(argv: list[str] | None = None) -> int:
    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.add_argument("events_dir", help="directory holding events-*.jsonl / "
                                       "timeline.jsonl")
    ap.add_argument("-o", "--out", default=None,
                    help="output path (default EVENTS_DIR/trace.json)")
    ap.add_argument("--check", action="store_true",
                    help="validate the converted trace and exit without "
                         "writing a file")
    args = ap.parse_args(argv)

    if not os.path.isdir(args.events_dir):
        print(f"ddp_trace: no such directory: {args.events_dir}",
              file=sys.stderr)
        return 1
    records = load_timeline(args.events_dir)
    if not records:
        print(f"ddp_trace: no event records under {args.events_dir}",
              file=sys.stderr)
        return 1

    trace = to_trace_events(records)
    problems = validate_trace(trace)
    for p in problems:
        print(f"ddp_trace: {p}", file=sys.stderr)
    if problems:
        return 1
    n = len(trace["traceEvents"])
    if args.check:
        print(f"ddp_trace: OK — {n} trace events from {len(records)} records")
        return 0

    out = args.out or os.path.join(args.events_dir, "trace.json")
    write_trace(trace, out)
    counters = sorted({e["name"] for e in trace["traceEvents"]
                       if e.get("ph") == "C"})
    instants = sorted({e["name"] for e in trace["traceEvents"]
                       if e.get("ph") == "i"})
    print(f"ddp_trace: wrote {out} ({n} events; "
          f"counters: {', '.join(counters) or 'none'}; "
          f"incidents: {', '.join(instants) or 'none'})")
    print("ddp_trace: open it at https://ui.perfetto.dev "
          "(Trace -> Open trace file)")
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
