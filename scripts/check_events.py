#!/usr/bin/env python
"""Validate observability events JSONL files against the schema.

Usage:
    python scripts/check_events.py EVENTS.jsonl [MORE.jsonl ...]
    python scripts/check_events.py --expect-order k1,k2,k3 timeline.jsonl

Usage (static, no JSONL files — cross-check emitters vs the registry):
    python scripts/check_events.py --schema-sync

Usage (protocol conformance — replay a timeline against the specs):
    python scripts/check_events.py --conformance EVENTS_DIR_OR_FILE

Usage (span lineage — trace-context integrity over a timeline):
    python scripts/check_events.py --lineage EVENTS_DIR_OR_FILE

``--lineage`` rebuilds the schema-v2 span trees
(``observability.critical_path.check_lineage``) and fails on any
orphan span (parent id never emitted — a process died without its
parent record, or a propagation bug dropped the context), on traces
with zero or multiple roots, and on parent edges that cross traces.

``--conformance`` replays each input (a merged ``timeline.jsonl`` or an
events *directory*, merged on the fly) against the protocol specs in
``analysis.protocol`` via ``analysis.conformance.check_timeline`` —
duplicate membership epochs, affinity admissions that still hit the
prefill tier, handoff attempt counts outside the NAK budget, and
routing to a dead engine all fail as PL405 findings.

Exit 0 when every record in every file is schema-valid (and, with
``--expect-order``, the listed kinds appear in that relative order);
exit 1 otherwise, printing each problem.  Used by tests/test_observability
and by the README smoke step; importable (``main(argv)``) so tests can
call it in-process.

``--schema-sync`` needs no event files: it scans the source tree with
the ddplint AST layer (``analysis.ast_rules.collect_emitted_kinds``)
and fails on drift between ``EventLog.emit(kind=...)`` literals and
``observability.schema.EVENT_KINDS`` — in BOTH directions: an emitted
kind missing from the registry (consumers would reject the record) and
a registered kind nothing emits (dead schema that silently rots).

Import-light on purpose: pulls in only the observability schema (stdlib),
never jax — it must run anywhere, including a bare CI box.
"""

from __future__ import annotations

import argparse
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from distributeddataparallel_tpu.observability.schema import (  # noqa: E402
    EVENT_KINDS,
    validate_file,
)


def check_order(path: str, kinds: list[str]) -> list[str]:
    """Check the listed kinds occur in the file in that relative order
    (other records may interleave).  Greedy first-occurrence matching:
    causal order in a (ts, seq)-sorted timeline."""
    import json

    want = list(kinds)
    with open(path) as fh:
        for line in fh:
            line = line.strip()
            if not line or not want:
                continue
            try:
                rec = json.loads(line)
            except json.JSONDecodeError:
                continue
            if rec.get("kind") == want[0]:
                want.pop(0)
    if want:
        return [
            f"{path}: expected kind order {','.join(kinds)} but never "
            f"reached {want[0]!r} (missing: {','.join(want)})"
        ]
    return []


def check_schema_sync(root: str | None = None) -> list[str]:
    """Two-way diff of statically-emitted kinds vs EVENT_KINDS."""
    from distributeddataparallel_tpu.analysis.ast_rules import (
        collect_emitted_kinds,
    )

    root = root or os.path.dirname(
        os.path.dirname(os.path.abspath(__file__))
    )
    emitted = collect_emitted_kinds(root)
    problems = []
    for kind in sorted(set(emitted) - set(EVENT_KINDS)):
        problems.append(
            f"schema-sync: kind {kind!r} emitted at "
            f"{', '.join(emitted[kind])} but not registered in "
            "observability.schema.EVENT_KINDS"
        )
    for kind in sorted(set(EVENT_KINDS) - set(emitted)):
        problems.append(
            f"schema-sync: kind {kind!r} registered in EVENT_KINDS but "
            "no emit site in the tree — dead schema (remove it or emit "
            "it)"
        )
    return problems


def main(argv: list[str] | None = None) -> int:
    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.add_argument("files", nargs="*", help="events JSONL file(s)")
    ap.add_argument(
        "--expect-order",
        default=None,
        metavar="K1,K2,...",
        help="comma-separated event kinds that must appear in this "
        "relative order in each file",
    )
    ap.add_argument(
        "--schema-sync",
        action="store_true",
        help="statically cross-check EventLog.emit kinds against "
        "EVENT_KINDS (both directions); needs no event files",
    )
    ap.add_argument(
        "--conformance",
        action="store_true",
        help="replay each input (timeline file or events dir) against "
        "the protocol specs (analysis.conformance, PL405)",
    )
    ap.add_argument(
        "--lineage",
        action="store_true",
        help="check span-tree lineage in each input (timeline file or "
        "events dir): every span's parent exists, exactly one root per "
        "trace, no cross-trace parent edges",
    )
    args = ap.parse_args(argv)
    if not args.files and not args.schema_sync:
        ap.error("provide events JSONL file(s) and/or --schema-sync")

    problems = []
    n_conformant = 0
    n_lineage = 0
    if args.schema_sync:
        problems.extend(check_schema_sync())
    for path in args.files:
        if not os.path.exists(path):
            problems.append(f"{path}: no such file")
            continue
        if os.path.isdir(path):
            if not (args.conformance or args.lineage):
                problems.append(
                    f"{path}: is a directory (only --conformance/"
                    "--lineage accept events directories)"
                )
                continue
        else:
            problems.extend(f"{path}: {p}" for p in validate_file(path))
            if args.expect_order:
                problems.extend(
                    check_order(
                        path,
                        [k.strip() for k in args.expect_order.split(",")],
                    )
                )
        if args.conformance:
            from distributeddataparallel_tpu.analysis import conformance

            found = conformance.check_path(path)
            problems.extend(str(f) for f in found)
            if not found:
                n_conformant += 1
        if args.lineage:
            from distributeddataparallel_tpu.analysis.conformance import (
                load_records,
            )
            from distributeddataparallel_tpu.observability.critical_path import (
                check_lineage,
            )

            found = check_lineage(load_records(path))
            problems.extend(f"{path}: lineage: {p}" for p in found)
            if not found:
                n_lineage += 1
    for p in problems:
        print(p, file=sys.stderr)
    if not problems:
        parts = []
        if args.files:
            parts.append(f"{len(args.files)} file(s) OK")
        if args.schema_sync:
            parts.append(
                f"schema-sync OK ({len(EVENT_KINDS)} kinds, "
                "emitters and registry agree)"
            )
        if args.conformance:
            parts.append(
                f"protocol conformance OK ({n_conformant} timeline(s))"
            )
        if args.lineage:
            parts.append(
                f"span lineage OK ({n_lineage} timeline(s))"
            )
        print("check_events: " + "; ".join(parts))
    return 1 if problems else 0


if __name__ == "__main__":
    raise SystemExit(main())
