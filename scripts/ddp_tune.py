#!/usr/bin/env python
"""Autotuner CLI: search, apply, and report over TunedConfig stores.

Usage:
    # Full search on an 8-fake-device CPU mesh; persist the winner:
    python scripts/ddp_tune.py search --model gpt2-small --devices 8 \
        --tune-dir .ddp_tune

    # What would `dpp.py --autotune apply` do on THIS host?  Prints the
    # dpp.py flags of the stored winner (or fails loudly on key drift):
    python scripts/ddp_tune.py apply --model gpt2-small --devices 8 \
        --tune-dir .ddp_tune

    # Every stored record, with its gain and drift accounting:
    python scripts/ddp_tune.py report --tune-dir .ddp_tune

    # CI smoke (tiny model, 2-trial search on 8 fake CPU devices;
    # asserts a persisted winner and schema-valid tune_* events):
    python scripts/ddp_tune.py --check

``search``/``apply`` need a live device mesh (they fingerprint the
topology); ``--devices N`` forces N fake CPU devices BEFORE the first
backend query, so a laptop can tune for — and inspect records of — an
N-chip data-parallel layout.  ``report`` is import-light: it reads
``*.tuned.json`` records without touching jax at all.

Exit codes: 0 = ok, 1 = usage error or (apply) no matching record,
2 = --check assertion failure.
"""

from __future__ import annotations

import argparse
import json
import os
import sys
import tempfile

ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, ROOT)

CHECK_EXIT = 2


def _force_devices(n: int) -> None:
    from distributeddataparallel_tpu import compat

    compat.configure_cpu_devices(n)


def _mesh():
    from distributeddataparallel_tpu.runtime.distributed import make_mesh

    return make_mesh()


def _fmt_s(v) -> str:
    return "-" if v is None else f"{v * 1e3:.1f}ms"


def cmd_search(args) -> int:
    if args.devices:
        _force_devices(args.devices)
    from distributeddataparallel_tpu.tuning import (
        TuningStore,
        search_model,
    )

    events = None
    if args.events_dir:
        from distributeddataparallel_tpu.observability import (
            EventLog,
            events_path,
        )

        os.makedirs(args.events_dir, exist_ok=True)
        events = EventLog(events_path(args.events_dir, 0), 0)
    exec_store = None
    if args.compile_cache:
        from distributeddataparallel_tpu.training.warm_start import (
            ExecutableStore,
        )

        exec_store = ExecutableStore(args.compile_cache)
    summary = search_model(
        args.model,
        mesh=_mesh(),
        seq=args.seq,
        top_k=args.trials,
        measure_steps=args.steps,
        seed=args.seed,
        tune_store=TuningStore(args.tune_dir),
        exec_store=exec_store,
        events=events,
    )
    if args.json:
        print(json.dumps(summary, default=str))
    else:
        for rec in summary["records"]:
            print(
                f"  {rec['trial']:<26} {rec['status']:<14}"
                f" pred={_fmt_s(rec['predicted_step_s'])}"
                f" meas={_fmt_s(rec['measured_step_s'])}"
            )
        w = summary["winner"]
        if w is None:
            print("no viable trial measured")
            return 1
        gain = summary.get("gain_frac")
        print(
            f"winner {w['trial']}  step={_fmt_s(w['measured_step_s'])}"
            + (f"  gain={gain * 100:+.1f}% vs baseline"
               if gain is not None else "")
            + f"\nsaved {summary['store_path']}"
        )
    return 0


def cmd_apply(args) -> int:
    if args.devices:
        _force_devices(args.devices)
    from distributeddataparallel_tpu.tuning import (
        TrialConfig,
        TuningStore,
        default_tuned_key,
    )

    mesh = _mesh()
    name = f"{args.model}@d{int(mesh.shape['data'])}"
    record = TuningStore(args.tune_dir).load(
        name, default_tuned_key(args.model, mesh, seq=args.seq)
    )
    if record is None:
        print(
            f"ddp_tune: no matching TunedConfig {name!r} under "
            f"{args.tune_dir} — run `ddp_tune.py search` first",
            file=sys.stderr,
        )
        return 1
    trial = TrialConfig.from_dict(record["config"])
    if args.json:
        print(json.dumps(record))
    else:
        # the argv fragment a wrapper script splices into its dpp.py call
        lm = args.model not in ("mlp", "cnn")
        print(" ".join(trial.cli_flags(lm=lm)))
    return 0


def cmd_report(args) -> int:
    from distributeddataparallel_tpu.tuning.store import TuningStore

    index = TuningStore(args.tune_dir).index()
    if not index:
        print(f"ddp_tune: no records under {args.tune_dir}")
        return 0
    if args.json:
        print(json.dumps(index))
        return 0
    for name, rec in index.items():
        gain = rec.get("gain_frac")
        print(
            f"{name}: {rec['config']}"
            f"  step={_fmt_s(rec.get('measured_step_s'))}"
            f"  score={rec.get('score'):.3g}"
            + (f"  gain={gain * 100:+.1f}%" if gain is not None else "")
        )
        for t in rec.get("trials", []):
            drift = t.get("drift_frac")
            print(
                f"    {t['trial']:<26} {t['status']:<14}"
                f" meas={_fmt_s(t.get('measured_step_s'))}"
                + (f" drift={drift * 100:+.0f}%"
                   if drift is not None else "")
            )
    return 0


def run_check() -> int:
    """CI smoke: a real (tiny) end-to-end search on 8 fake CPU devices.

    Asserts the three things the subsystem promises: a winner record is
    persisted under the topology-scoped name, every emitted tune_* event
    validates against the schema, and both tune_trial and tune_result
    kinds actually appear.
    """
    _force_devices(8)
    from distributeddataparallel_tpu.observability import (
        EventLog,
        events_path,
    )
    from distributeddataparallel_tpu.observability.schema import (
        validate_file,
    )
    from distributeddataparallel_tpu.tuning import (
        SearchSpace,
        TuningStore,
        search_model,
    )

    with tempfile.TemporaryDirectory(prefix="ddp_tune_check") as tmp:
        ev_path = events_path(tmp, 0)
        summary = search_model(
            "mlp",
            mesh=_mesh(),
            space=SearchSpace(
                batch_per_chip=(8, 16), accum_steps=(1,), remat=(False,),
                zero=(0, 1), moment_dtype=("f32",),
            ),
            top_k=2,
            warmup_steps=1,
            measure_steps=2,
            seed=0,
            tune_store=TuningStore(os.path.join(tmp, "tuned")),
            events=EventLog(ev_path, 0),
        )
        problems = []
        if summary["winner"] is None:
            problems.append("search measured no winner")
        store_path = summary.get("store_path")
        if not (store_path and os.path.exists(store_path)):
            problems.append(f"winner record not persisted: {store_path!r}")
        problems += validate_file(ev_path)
        kinds = {
            json.loads(line)["kind"] for line in open(ev_path)
        }
        for want in ("tune_trial", "tune_result"):
            if want not in kinds:
                problems.append(f"no {want} event emitted")
        if problems:
            for p in problems:
                print(f"ddp_tune --check: {p}", file=sys.stderr)
            return CHECK_EXIT
    print(
        "ddp_tune --check: winner "
        f"{summary['winner']['trial']} persisted, events schema-valid"
    )
    return 0


def build_parser() -> argparse.ArgumentParser:
    p = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    p.add_argument("cmd", nargs="?", choices=("search", "apply", "report"),
                   help="search: run the tuner and persist the winner; "
                        "apply: print the stored winner's dpp.py flags; "
                        "report: dump every record with drift accounting")
    p.add_argument("--model", default="gpt2-small",
                   help="mlp | cnn | tiny-lm | gpt2-small (alias gpt2)")
    p.add_argument("--devices", type=int, default=0, metavar="N",
                   help="force N fake CPU devices (0 = use the real "
                        "backend as-is)")
    p.add_argument("--seq", type=int, default=128)
    p.add_argument("--trials", type=int, default=3,
                   help="top-K candidates to measure after pruning")
    p.add_argument("--steps", type=int, default=4,
                   help="measured steps per candidate")
    p.add_argument("--seed", type=int, default=0)
    p.add_argument("--tune-dir", default=".ddp_tune",
                   help="TunedConfig store directory")
    p.add_argument("--compile-cache", default=None, metavar="DIR",
                   help="warm-start ExecutableStore for background "
                        "candidate precompiles")
    p.add_argument("--events-dir", default=None,
                   help="write tune_* events as observability JSONL here")
    p.add_argument("--json", action="store_true",
                   help="machine-readable output")
    p.add_argument("--check", action="store_true",
                   help="CI smoke: tiny 2-trial search on 8 fake CPU "
                        "devices; nonzero unless a winner persists and "
                        "events validate")
    return p


def main(argv: list[str] | None = None) -> int:
    args = build_parser().parse_args(argv)
    if args.check:
        return run_check()
    if args.cmd == "search":
        return cmd_search(args)
    if args.cmd == "apply":
        return cmd_apply(args)
    if args.cmd == "report":
        return cmd_report(args)
    build_parser().print_usage(sys.stderr)
    return 1


if __name__ == "__main__":
    sys.exit(main())
