#!/usr/bin/env python
"""Live gang monitor: tail a run's per-worker event files.

Usage:
    python scripts/ddp_monitor.py EVENTS_DIR            # one-shot status
    python scripts/ddp_monitor.py EVENTS_DIR --follow   # live tail
    python scripts/ddp_monitor.py EVENTS_DIR --follow --interval 0.5

Usage (scrape mode — no filesystem access to the run needed):
    python scripts/ddp_monitor.py --scrape H1:P1,H2:P2 [--follow]

``--scrape`` is the pull-based counterpart for the serving fleet: each
fleet process exposes a live ``/metrics`` endpoint
(``observability.httpmetrics``; the router prints its address, workers
advertise theirs in the hello message), and the monitor polls the
comma-separated endpoints and renders one row per process —
``serve_tok_s`` on engines, ``router_queue_depth`` and the per-tier
TTFT gauges on the router.  A dead endpoint is a ``DOWN`` row, not a
crash; exit 1 only when every endpoint is down.

One-shot mode prints a per-rank table (last step, last step time, last
MFU, seconds since the rank last wrote, nan-skips, status) plus every
fired alert, then exits **2 if any alert fired**, 0 when healthy, 1
when there is nothing to read — so a supervisor script can `ddp_monitor
$DIR || page_someone`.  Follow mode re-reads only the bytes appended
since the last poll (byte offsets per file, torn trailing lines left
unconsumed for the next poll) and streams alerts as they land.

Reads ``events-p*.jsonl`` and ``events-supervisor.jsonl`` directly —
no merge needed, files still being written are fine.

Import-light on purpose: pure stdlib, never jax — this runs on the
machine (or laptop) watching the run, not in the gang.
"""

from __future__ import annotations

import argparse
import glob
import json
import os
import sys
import time

ALERT_EXIT = 2


class _Tail:
    """Incremental reader for one append-only JSONL file.  Keeps a byte
    offset; a trailing line without a newline is left for the next poll
    (the writer is mid-append), so records are never torn by the
    reader."""

    def __init__(self, path: str):
        self.path = path
        self.offset = 0

    def poll(self) -> list[dict]:
        try:
            with open(self.path, "rb") as fh:
                fh.seek(self.offset)
                chunk = fh.read()
        except OSError:
            return []
        if not chunk:
            return []
        nl = chunk.rfind(b"\n")
        if nl < 0:
            return []  # only a partial line so far
        self.offset += nl + 1
        out = []
        for line in chunk[:nl].split(b"\n"):
            line = line.strip()
            if not line:
                continue
            try:
                out.append(json.loads(line))
            except json.JSONDecodeError:
                continue  # torn line from a killed writer incarnation
        return out


class GangState:
    """Per-rank rollup of everything the status table shows."""

    def __init__(self):
        self.ranks: dict[int, dict] = {}
        self.alerts: list[dict] = []
        self.supervisor: list[dict] = []
        self.epoch: int | None = None
        self.roster: set[str] | None = None

    def _rank(self, proc: int) -> dict:
        return self.ranks.setdefault(proc, {
            "last_ts": None, "last_step": None, "last_step_s": None,
            "last_mfu": None, "status": "running", "nan_skips": 0,
            "alerts": 0, "incarnations": 0,
        })

    def ingest(self, rec: dict) -> None:
        proc = rec.get("proc")
        kind = rec.get("kind")
        if kind == "membership_epoch":
            # Worker or supervisor; the highest epoch wins.
            ep = rec.get("epoch")
            if isinstance(ep, int) and (self.epoch is None
                                        or ep >= self.epoch):
                self.epoch = ep
                self.roster = set(rec.get("roster") or [])
            return
        if proc == "supervisor":
            if kind in ("restart_attempt", "restart_exhausted",
                        "gang_resize"):
                self.supervisor.append(rec)
            return
        if not isinstance(proc, int):
            return
        r = self._rank(proc)
        ts = rec.get("ts")
        if isinstance(ts, (int, float)):
            r["last_ts"] = max(r["last_ts"] or 0.0, float(ts))
        if kind == "run_start":
            r["incarnations"] += 1
            r["status"] = "running"
        elif kind == "run_end":
            r["status"] = str(rec.get("status", "ended"))
        elif kind == "span" and rec.get("name") == "step":
            if isinstance(rec.get("step"), int):
                r["last_step"] = rec["step"]
            if isinstance(rec.get("dur_s"), (int, float)):
                r["last_step_s"] = float(rec["dur_s"])
        elif kind == "mfu":
            if isinstance(rec.get("mfu"), (int, float)):
                r["last_mfu"] = float(rec["mfu"])
            if isinstance(rec.get("step"), int):
                r["last_step"] = max(r["last_step"] or 0, rec["step"])
        elif kind == "nan_skip":
            r["nan_skips"] += 1
        elif kind == "alert":
            r["alerts"] += 1
            self.alerts.append(rec)

    def table(self, now: float | None = None) -> str:
        now = time.time() if now is None else now
        lines = []
        if self.epoch is not None:
            lines.append(
                f"membership epoch {self.epoch} "
                f"({len(self.roster or ())} member(s))"
            )
        lines.append(
            f"{'rank':>4}  {'step':>8}  {'step_s':>9}  {'mfu':>6}  "
            f"{'idle_s':>7}  {'nan':>4}  {'alerts':>6}  {'epoch':>5}  "
            "status",
        )
        def fmt(value, spec: str) -> str:
            return "-" if value is None else format(value, spec)

        for proc in sorted(self.ranks):
            r = self.ranks[proc]
            idle = now - r["last_ts"] if r["last_ts"] else None
            # A rank absent from the current roster left the gang at the
            # last resize — the elastic runtime runs on without it.
            member = "-" if self.epoch is None else (
                str(self.epoch)
                if self.roster is None or f"proc{proc}" in self.roster
                else "out"
            )
            lines.append(
                f"{proc:>4}  "
                f"{fmt(r['last_step'], 'd'):>8}  "
                f"{fmt(r['last_step_s'], '.4f'):>9}  "
                f"{fmt(r['last_mfu'], '.3f'):>6}  "
                f"{fmt(idle, '.1f'):>7}  "
                f"{r['nan_skips']:>4}  {r['alerts']:>6}  {member:>5}  "
                f"{r['status']}"
            )
        for rec in self.supervisor[-3:]:
            if rec.get("kind") == "gang_resize":
                lines.append(
                    f"  supervisor: gang_resize {rec.get('old_size')} -> "
                    f"{rec.get('new_size')} (epoch {rec.get('epoch')})"
                )
            else:
                lines.append(
                    f"  supervisor: {rec.get('kind')} attempt "
                    f"{rec.get('attempt')}"
                )
        return "\n".join(lines)


def _fmt_alert(rec: dict) -> str:
    return (f"ALERT [{rec.get('rule')}] rank {rec.get('proc')} "
            f"step {rec.get('step')}: value {rec.get('value')} vs "
            f"threshold {rec.get('threshold')}")


#: series promoted to columns in the scrape table (everything else is
#: rolled up into a "+N more" count per endpoint)
_SCRAPE_COLUMNS = (
    "serve_tok_s",
    "router_queue_depth",
    "fleet_prefill_p50_ttft_s",
    "fleet_prefill_p99_ttft_s",
    "fleet_decode_p50_ttft_s",
    "fleet_decode_p99_ttft_s",
)


def scrape_table(targets: list[str]) -> tuple[str, int]:
    """Poll every ``host:port`` /metrics endpoint once; returns the
    rendered table and the number of endpoints that answered."""
    sys.path.insert(
        0, os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    )
    from distributeddataparallel_tpu.observability.httpmetrics import (
        scrape,
    )

    lines = []
    up = 0
    for addr in targets:
        try:
            series = scrape(addr)
        except (OSError, ValueError) as exc:
            lines.append(f"{addr:<22}  DOWN ({exc})")
            continue
        up += 1
        cells = [
            f"{name}={series[name]:g}"
            for name in _SCRAPE_COLUMNS if name in series
        ]
        extra = len(series) - len(cells)
        if extra > 0:
            cells.append(f"+{extra} more")
        lines.append(
            f"{addr:<22}  " + ("  ".join(cells) if cells else "(empty)")
        )
    return "\n".join(lines), up


def _run_scrape(args) -> int:
    targets = [t.strip() for t in args.scrape.split(",") if t.strip()]
    if not targets:
        print("ddp_monitor: --scrape needs host:port[,host:port...]",
              file=sys.stderr)
        return 1
    if not args.follow:
        table, up = scrape_table(targets)
        print(table)
        return 0 if up else 1
    t_end = (time.time() + args.max_seconds
             if args.max_seconds is not None else None)
    up_ever = 0
    try:
        while True:
            table, up = scrape_table(targets)
            up_ever = max(up_ever, up)
            print(table)
            print("---")
            if t_end is not None and time.time() >= t_end:
                break
            time.sleep(args.interval)
    except KeyboardInterrupt:
        pass
    return 0 if up_ever else 1


def _tails(events_dir: str, known: dict[str, _Tail]) -> list[_Tail]:
    for path in sorted(glob.glob(os.path.join(events_dir, "events-*.jsonl"))):
        if path not in known:
            known[path] = _Tail(path)
    return list(known.values())


def main(argv: list[str] | None = None) -> int:
    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.add_argument("events_dir", nargs="?", default=None,
                    help="directory holding events-*.jsonl (omit with "
                         "--scrape)")
    ap.add_argument("--follow", action="store_true",
                    help="keep tailing (one-shot status is the default)")
    ap.add_argument("--interval", type=float, default=2.0,
                    help="poll interval in follow mode (default 2s)")
    ap.add_argument("--max-seconds", type=float, default=None,
                    help="stop following after this long (for scripting "
                         "and tests; default: until interrupted)")
    ap.add_argument("--scrape", default=None, metavar="HOST:PORT,...",
                    help="poll live /metrics endpoints instead of "
                         "tailing event files")
    args = ap.parse_args(argv)

    if args.scrape is not None:
        return _run_scrape(args)
    if args.events_dir is None:
        ap.error("provide an events directory (or --scrape endpoints)")
    if not os.path.isdir(args.events_dir):
        print(f"ddp_monitor: no such directory: {args.events_dir}",
              file=sys.stderr)
        return 1

    state = GangState()
    tails: dict[str, _Tail] = {}

    def drain() -> list[dict]:
        fresh_alerts = []
        for tail in _tails(args.events_dir, tails):
            for rec in tail.poll():
                n_before = len(state.alerts)
                state.ingest(rec)
                fresh_alerts.extend(state.alerts[n_before:])
        return fresh_alerts

    if not args.follow:
        drain()
        if not state.ranks and not state.supervisor:
            print(f"ddp_monitor: no event records under {args.events_dir}",
                  file=sys.stderr)
            return 1
        print(state.table())
        for rec in state.alerts:
            print(_fmt_alert(rec))
        return ALERT_EXIT if state.alerts else 0

    t_end = (time.time() + args.max_seconds
             if args.max_seconds is not None else None)
    try:
        while True:
            for rec in drain():
                print(_fmt_alert(rec))
            if state.ranks:
                print(state.table())
                print("---")
            if t_end is not None and time.time() >= t_end:
                break
            time.sleep(args.interval)
    except KeyboardInterrupt:
        pass
    return ALERT_EXIT if state.alerts else 0


if __name__ == "__main__":
    raise SystemExit(main())
