#!/usr/bin/env python
"""Offline run report: one events dir in, one markdown (or JSON) out.

Usage:
    python scripts/ddp_report.py EVENTS_DIR            # markdown to stdout
    python scripts/ddp_report.py EVENTS_DIR --json     # machine-readable
    python scripts/ddp_report.py EVENTS_DIR -o report.md

Consumes the merged ``timeline.jsonl`` a run leaves behind (merging the
per-worker files itself when the run died before the exit-time merge)
and renders the four performance-attribution views:

- **Goodput** — wall time split into productive / compile / checkpoint /
  eval / restart / stall, reconstructed across every incarnation of a
  supervised run (``observability.goodput``);
- **MFU trend** — the per-window ``mfu`` events as a table (cost model
  vs hardware peak);
- **Memory** — per-rank live-array / device high-water marks from the
  ``memory`` and ``exec_memory`` events;
- **Stragglers** — per-rank step stats and cross-rank skew attribution
  (``observability.straggler``).

Sections a run didn't record (no --mfu, single rank, gang dead before
any worker wrote) degrade to an explanatory line, never a crash — the
report is most needed for the runs that ended badly.

Import-light on purpose: stdlib + the stdlib-only observability modules,
never jax — it must run on a laptop holding only the events dir.
"""

from __future__ import annotations

import argparse
import json
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from distributeddataparallel_tpu.analysis.conformance import (  # noqa: E402
    check_timeline,
)
from distributeddataparallel_tpu.observability.critical_path import (  # noqa: E402
    check_lineage,
    request_decompositions,
    tier_rollups,
    ttft_rollup,
)
from distributeddataparallel_tpu.observability.events import (  # noqa: E402
    load_timeline,
)
from distributeddataparallel_tpu.observability.goodput import (  # noqa: E402
    goodput_from_timeline,
)
from distributeddataparallel_tpu.observability.pipeline import (  # noqa: E402
    PHASE_COLUMNS,
    measured_bubble_fraction,
)
from distributeddataparallel_tpu.observability.straggler import (  # noqa: E402
    straggler_report,
)


def _fmt_bytes(n) -> str:
    if n is None:
        return "-"
    for unit in ("B", "KiB", "MiB", "GiB", "TiB"):
        if abs(n) < 1024 or unit == "TiB":
            return f"{n:.1f} {unit}" if unit != "B" else f"{n} B"
        n /= 1024
    return f"{n}"


def _pct(x) -> str:
    return "-" if x is None else f"{100 * x:.2f}%"


def analyze(records: list[dict]) -> dict:
    """Everything the renderers need, as plain data — the --json face."""
    worker_procs = sorted(
        {r["proc"] for r in records if isinstance(r.get("proc"), int)}
    )
    out = {
        "n_records": len(records),
        "worker_procs": worker_procs,
        "goodput": None,
        "mfu": [],
        "memory": {},
        "exec_memory": [],
        "straggler": None,
        "pipeline": measured_bubble_fraction(records),
        "restarts": [],
        "elasticity": None,
        "integrity": None,
        "alerts": [],
        "lint": [],
        "run_summary": None,
        "serving": None,
        "fleet": None,
        "ttft_decomposition": None,
        "tuning": None,
    }
    if worker_procs:
        out["goodput"] = goodput_from_timeline(records, proc=worker_procs[0])
        out["straggler"] = straggler_report(records)

    for r in records:
        kind = r.get("kind")
        if kind == "mfu":
            out["mfu"].append({
                "step": r.get("step"),
                "mfu": r.get("mfu"),
                "hfu": r.get("hfu"),
                "model_flops_per_s": r.get("model_flops_per_s"),
            })
        elif kind == "memory":
            proc = r.get("proc")
            mem = out["memory"].setdefault(proc, {
                "samples": 0,
                "live_hwm_bytes": 0,
                "device_peak_bytes": None,
            })
            mem["samples"] += 1
            mem["live_hwm_bytes"] = max(
                mem["live_hwm_bytes"], r.get("live_hwm_bytes") or 0
            )
            if r.get("device_peak_bytes") is not None:
                mem["device_peak_bytes"] = max(
                    mem["device_peak_bytes"] or 0, r["device_peak_bytes"]
                )
        elif kind == "exec_memory":
            out["exec_memory"].append(
                {k: v for k, v in r.items() if k not in ("v", "seq")}
            )
        elif kind in ("restart_attempt", "restart_exhausted"):
            out["restarts"].append({
                "kind": kind,
                "attempt": r.get("attempt"),
                "failed": r.get("failed"),
            })
        elif kind in ("membership_epoch", "gang_resize", "resize_downtime",
                      "gang_suspect", "rdzv_rehost", "gang_verdict"):
            el = out["elasticity"]
            if el is None:
                el = out["elasticity"] = {
                    "epochs": {}, "resizes": [], "downtimes": {},
                    "suspects": [], "rehosts": [], "verdict": None,
                }
            if kind == "gang_suspect":
                el["suspects"].append({
                    "member": r.get("member"),
                    "age_s": r.get("age_s"),
                    "epoch": r.get("epoch"),
                })
            elif kind == "rdzv_rehost":
                el["rehosts"].append({
                    "generation": r.get("generation"),
                    "owner": r.get("owner"),
                })
            elif kind == "gang_verdict":
                # At most one per run (the supervisor's terminal ladder
                # record); keep the last in case a merged timeline holds
                # several supervised sub-runs.
                el["verdict"] = {
                    "rung": r.get("rung"),
                    "fault": r.get("fault"),
                    "fault_kind": r.get("fault_kind"),
                }
            elif kind == "membership_epoch":
                # Worker and supervisor may both emit an epoch record;
                # keyed by epoch so duplicates collapse (last wins).
                el["epochs"][r.get("epoch")] = {
                    "epoch": r.get("epoch"),
                    "size": r.get("size"),
                    "roster": r.get("roster") or [],
                }
            elif kind == "gang_resize":
                # Every survivor (and the supervisor) reports the same
                # transition; collapse duplicates of one epoch.
                if not any(
                    z["epoch"] == r.get("epoch") for z in el["resizes"]
                ):
                    el["resizes"].append({
                        "epoch": r.get("epoch"),
                        "old_size": r.get("old_size"),
                        "new_size": r.get("new_size"),
                        "left": r.get("left") or [],
                        "joined": r.get("joined") or [],
                    })
            else:
                if isinstance(r.get("seconds"), (int, float)):
                    ep = r.get("epoch")
                    el["downtimes"][ep] = max(
                        el["downtimes"].get(ep, 0.0), r["seconds"]
                    )
        elif kind in ("sdc_check", "sdc_detect", "sdc_evict"):
            ig = out["integrity"]
            if ig is None:
                ig = out["integrity"] = {
                    "checks": 0, "detects": [], "evictions": [],
                }
            if kind == "sdc_check":
                ig["checks"] += 1
            elif kind == "sdc_detect":
                ig["detects"].append({
                    "step": r.get("step"),
                    "rank": r.get("rank"),
                    "ranks": r.get("ranks") or [],
                    "leaves": r.get("leaves") or [],
                    "method": r.get("method"),
                    "tie": r.get("tie"),
                })
            else:
                ig["evictions"].append({
                    "step": r.get("step"),
                    "rank": r.get("rank"),
                })
        elif kind == "lint_report":
            out["lint"].append({
                "layer": r.get("layer"),
                "n_findings": r.get("n_findings"),
                "rules": r.get("rules"),
                "findings": r.get("findings") or [],
            })
        elif kind == "alert":
            out["alerts"].append({
                "rule": r.get("rule"),
                "proc": r.get("proc"),
                "step": r.get("step"),
                "ts": r.get("ts"),
                "value": r.get("value"),
                "threshold": r.get("threshold"),
            })
        elif kind == "run_summary":
            # Last one wins: the final incarnation's summary is the one
            # that reflects the whole (resumed) run.
            out["run_summary"] = {
                k: v for k, v in r.items() if k not in ("v", "seq", "kind")
            }
        elif kind in ("request_admit", "prefill_chunk", "decode_step",
                      "request_done", "kv_evict", "prefix_hit",
                      "spec_verify"):
            s = out["serving"]
            if s is None:
                s = out["serving"] = {
                    "admitted": 0, "completed": 0, "tokens_out": 0,
                    "prefill_chunks": 0, "decode_steps": 0,
                    "active_sum": 0, "active_max": 0,
                    "evictions": {"lru": 0, "preempt": 0},
                    "evicted_blocks": 0, "ttft_s": [],
                    "first_ts": None, "last_ts": None,
                    "ctx_tokens": 0, "prefix_hits": 0,
                    "prefix_hit_tokens": 0, "spec_dispatches": 0,
                    "spec_drafted": 0, "spec_accepted": 0,
                    "spec_rows": 0, "accept_hist": {},
                }
            ts = r.get("ts")
            if isinstance(ts, (int, float)):
                s["first_ts"] = ts if s["first_ts"] is None \
                    else min(s["first_ts"], ts)
                s["last_ts"] = ts if s["last_ts"] is None \
                    else max(s["last_ts"], ts)
            if kind == "request_admit":
                s["admitted"] += 1
                s["ctx_tokens"] += r.get("ctx_tokens") or 0
            elif kind == "prefill_chunk":
                s["prefill_chunks"] += 1
            elif kind == "decode_step":
                s["decode_steps"] += 1
                n = r.get("n_active") or 0
                s["active_sum"] += n
                s["active_max"] = max(s["active_max"], n)
            elif kind == "request_done":
                s["completed"] += 1
                s["tokens_out"] += r.get("tokens") or 0
                if isinstance(r.get("ttft_s"), (int, float)):
                    s["ttft_s"].append(r["ttft_s"])
            elif kind == "kv_evict":
                reason = r.get("reason") or "lru"
                s["evictions"][reason] = (
                    s["evictions"].get(reason, 0) + 1
                )
                s["evicted_blocks"] += r.get("blocks") or 0
            elif kind == "prefix_hit":
                s["prefix_hits"] += 1
                s["prefix_hit_tokens"] += r.get("tokens") or 0
            elif kind == "spec_verify":
                s["spec_dispatches"] += 1
                s["spec_drafted"] += r.get("drafted") or 0
                s["spec_accepted"] += r.get("accepted") or 0
                rows = r.get("rows") or 0
                s["spec_rows"] += rows
                if rows:
                    # accept-length histogram, bucketed by the
                    # dispatch's mean accepted tokens per row
                    b = int((r.get("accepted") or 0) // rows)
                    s["accept_hist"][b] = s["accept_hist"].get(b, 0) + 1
        elif kind in ("route_admit", "kv_handoff", "engine_verdict",
                      "tier_summary"):
            f = out["fleet"]
            if f is None:
                f = out["fleet"] = {
                    "routed": 0, "affinity_hits": 0,
                    "queue_depth_max": 0,
                    "handoffs": 0, "handoff_bytes": 0,
                    "handoff_s": [], "redelivered": 0,
                    "verdicts": [], "tiers": {},
                }
            if kind == "route_admit":
                f["routed"] += 1
                if r.get("affinity"):
                    f["affinity_hits"] += 1
                f["queue_depth_max"] = max(
                    f["queue_depth_max"], r.get("queue_depth") or 0
                )
            elif kind == "kv_handoff":
                f["handoffs"] += 1
                f["handoff_bytes"] += r.get("bytes") or 0
                if isinstance(r.get("handoff_s"), (int, float)):
                    f["handoff_s"].append(r["handoff_s"])
                if (r.get("attempts") or 1) > 1:
                    f["redelivered"] += 1
            elif kind == "engine_verdict":
                f["verdicts"].append({
                    k: r.get(k) for k in (
                        "engine", "rung", "tier", "requeued", "reason",
                    )
                })
            else:  # tier_summary (one rollup per tier per run)
                f["tiers"][r.get("tier")] = {
                    k: r.get(k) for k in (
                        "completed", "p50_ttft_s", "p99_ttft_s",
                        "p50_tpot_s", "p99_tpot_s",
                    ) if r.get(k) is not None
                }
        elif kind in ("tune_trial", "tune_result"):
            t = out["tuning"]
            if t is None:
                t = out["tuning"] = {
                    "trials": [], "result": None, "drift_fracs": [],
                }
            if kind == "tune_trial":
                t["trials"].append({
                    k: r.get(k) for k in (
                        "trial", "status", "predicted_step_s",
                        "measured_step_s", "score", "mfu", "drift_frac",
                        "warm_mode",
                    )
                })
                if isinstance(r.get("drift_frac"), (int, float)):
                    t["drift_fracs"].append(r["drift_frac"])
            else:
                # last one wins — a search followed by apply runs in the
                # same events dir reports the final applied state
                t["result"] = {
                    k: r.get(k) for k in (
                        "mode", "winner", "applied", "score", "mfu",
                        "gain_frac", "n_trials", "n_measured",
                        "store_path",
                    )
                }
    if out["serving"]:
        s = out["serving"]
        span = (
            (s["last_ts"] - s["first_ts"])
            if s["first_ts"] is not None else 0.0
        )
        s["tok_s"] = s["tokens_out"] / span if span > 0 else None
        s["mean_active"] = (
            s["active_sum"] / s["decode_steps"]
            if s["decode_steps"] else 0.0
        )
        ttfts = sorted(s.pop("ttft_s"))
        s["ttft_p50_s"] = _quantile(ttfts, 0.50)
        s["ttft_p99_s"] = _quantile(ttfts, 0.99)
        s["prefix_hit_frac"] = (
            s["prefix_hits"] / s["admitted"] if s["admitted"] else None
        )
        s["prefill_flops_avoided_frac"] = (
            s["prefix_hit_tokens"] / s["ctx_tokens"]
            if s["ctx_tokens"] else None
        )
        s["spec_accept_mean"] = (
            s["spec_accepted"] / s["spec_rows"]
            if s["spec_rows"] else None
        )
        s["accept_hist"] = {
            str(k): s["accept_hist"][k] for k in sorted(s["accept_hist"])
        }
    if out["fleet"]:
        f = out["fleet"]
        hs = sorted(f.pop("handoff_s"))
        f["handoff_s_mean"] = (sum(hs) / len(hs)) if hs else None
        f["handoff_s_p99"] = _quantile(hs, 0.99) if hs else None
        f["affinity_frac"] = (
            f["affinity_hits"] / f["routed"] if f["routed"] else None
        )
    if out["elasticity"]:
        el = out["elasticity"]
        # dicts keyed by epoch -> sorted lists for the --json face
        el["epochs"] = [el["epochs"][k]
                        for k in sorted(el["epochs"], key=lambda e: (e is None, e))]
        el["downtimes"] = [
            {"epoch": k, "seconds": v}
            for k, v in sorted(el["downtimes"].items(),
                               key=lambda kv: (kv[0] is None, kv[0]))
        ]
        el["n_resizes"] = len(el["resizes"])
        el["resize_downtime_s"] = round(
            sum(d["seconds"] for d in el["downtimes"]), 3
        )
        # Restart-seconds reclaimed: each resize replaced one cold
        # restart.  With restarts in the SAME timeline the mean restart
        # gap (goodput restart bucket / count) is the in-run baseline;
        # without one the comparison lives in bench elastic_resize.
        el["restart_reclaimed_s"] = None
        g = out["goodput"]
        if g and g.get("restarts") and el["downtimes"]:
            mean_restart = g["buckets"].get("restart", 0.0) / g["restarts"]
            if mean_restart > 0:
                el["restart_reclaimed_s"] = round(sum(
                    max(0.0, mean_restart - d["seconds"])
                    for d in el["downtimes"]
                ), 3)

    # TTFT decomposition: rebuild the schema-v2 span trees and account
    # for every completed request's first-token latency (queue wait /
    # prefill / handoff / decode), with the gateable share headlines
    # and the lineage problems (orphan spans, multi-root traces).
    decomps = request_decompositions(records)
    if decomps:
        roll = ttft_rollup(decomps)
        roll["tiers"] = tier_rollups(decomps)
        roll["lineage_problems"] = check_lineage(records)
        out["ttft_decomposition"] = roll

    # Protocol conformance: replay the whole timeline against the
    # declared state machines (analysis.protocol) — PL405 per violation.
    out["conformance"] = [str(f) for f in check_timeline(records)]
    return out


def _quantile(sorted_vals: list, q: float):
    """Nearest-rank quantile over an already-sorted list (stdlib-only —
    this script must run without numpy)."""
    if not sorted_vals:
        return None
    idx = min(len(sorted_vals) - 1, int(round(q * (len(sorted_vals) - 1))))
    return sorted_vals[idx]


def render_markdown(a: dict, events_dir: str) -> str:
    lines = [f"# Run report — `{events_dir}`", ""]
    if not a["n_records"]:
        lines.append("No event records found — nothing ever wrote to this "
                     "directory.")
        return "\n".join(lines) + "\n"
    if not a["worker_procs"]:
        lines += [
            f"{a['n_records']} supervisor-only records — the gang died "
            "before any worker wrote events.",
            "",
        ]

    # -- Goodput ------------------------------------------------------
    lines += ["## Goodput", ""]
    g = a["goodput"]
    if g is None:
        lines.append("No worker run_start in the timeline — goodput "
                     "cannot be attributed.")
    else:
        lines += [
            f"**{_pct(g['goodput'])}** of {g['total_s']:.1f}s wall time "
            f"was productive ({g['restarts']} restart(s)).",
            "",
            "| bucket | seconds | share |",
            "|---|---:|---:|",
            f"| productive | {g['productive_s']:.2f} | "
            f"{_pct(g['goodput'])} |",
        ]
        for name, secs in g["buckets"].items():
            share = secs / g["total_s"] if g["total_s"] else None
            lines.append(f"| {name} | {secs:.2f} | {_pct(share)} |")
        if g["restarts"]:
            lines += ["", f"Incarnations ({len(g['incarnations'])}):", ""]
            for i, inc in enumerate(g["incarnations"]):
                lines.append(
                    f"- attempt {i}: {inc['total_s']:.1f}s, "
                    f"status `{inc['status']}`"
                )
    lines.append("")

    # -- MFU ----------------------------------------------------------
    lines += ["## MFU trend", ""]
    if not a["mfu"]:
        lines.append("No `mfu` events — run with `--mfu` to record the "
                     "cost-model utilization per throughput window.")
    else:
        lines += ["| step | MFU | HFU | model FLOP/s |", "|---:|---:|---:|---:|"]
        for m in a["mfu"]:
            lines.append(
                f"| {m['step']} | {_pct(m['mfu'])} | {_pct(m['hfu'])} | "
                f"{m['model_flops_per_s']:.3e} |"
            )
        vals = [m["mfu"] for m in a["mfu"] if m["mfu"] is not None]
        if vals:
            lines += [
                "",
                f"Mean MFU {_pct(sum(vals) / len(vals))} over "
                f"{len(vals)} window(s); last {_pct(vals[-1])}.",
            ]
    lines.append("")

    # -- Memory -------------------------------------------------------
    lines += ["## Memory high-water marks", ""]
    if not a["memory"]:
        lines.append("No `memory` events — run with `--memory-telemetry` "
                     "to sample live-array/device memory at window "
                     "boundaries.")
    else:
        lines += [
            "| rank | samples | live-array HWM | device peak |",
            "|---:|---:|---:|---:|",
        ]
        for proc, mem in sorted(a["memory"].items(), key=lambda kv: str(kv[0])):
            lines.append(
                f"| {proc} | {mem['samples']} | "
                f"{_fmt_bytes(mem['live_hwm_bytes'])} | "
                f"{_fmt_bytes(mem['device_peak_bytes'])} |"
            )
    for e in a["exec_memory"]:
        parts = [
            f"{k.replace('_bytes', '')} {_fmt_bytes(v)}"
            for k, v in e.items()
            if k.endswith("_bytes") and v is not None
        ]
        lines += [
            "",
            f"Compiler budget for `{e.get('label')}` (rank {e.get('proc')}): "
            + ", ".join(parts),
        ]
    lines.append("")

    # -- Stragglers ---------------------------------------------------
    lines += ["## Stragglers", ""]
    s = a["straggler"]
    if s is None:
        lines.append("No step spans in the timeline — nothing ran.")
    else:
        lines += [
            "| rank | steps | mean step | max step |",
            "|---:|---:|---:|---:|",
        ]
        for proc, st in sorted(s["ranks"].items(), key=lambda kv: str(kv[0])):
            lines.append(
                f"| {proc} | {st['steps']} | {st['mean_step_s'] * 1e3:.2f} ms"
                f" | {st['max_step_s'] * 1e3:.2f} ms |"
            )
        if s["n_ranks"] < 2:
            lines += ["", "Single-rank gang: cross-rank skew is undefined."]
        elif s["steps_compared"]:
            lines += [
                "",
                f"Across {s['steps_compared']} gang steps: mean skew "
                f"{s['skew_mean_s'] * 1e3:.2f} ms, max "
                f"{s['skew_max_s'] * 1e3:.2f} ms; slowest rank "
                f"**{s['slowest_rank']}** (last to finish "
                f"{s['slowest_counts'].get(s['slowest_rank'], 0)} times).",
                "",
                "| skew bucket | gang steps |",
                "|---|---:|",
            ]
            for label, count in s["skew_histogram"].items():
                lines.append(f"| {label} | {count} |")
    lines.append("")

    # -- Pipeline -----------------------------------------------------
    lines += ["## Pipeline", ""]
    pp = a["pipeline"]
    if pp is None:
        lines.append("No `pp_phase` events — not a pipeline-parallel run "
                     "(train with `--pp N --pp-schedule 1f1b|zb` to "
                     "record the schedule's phase counters).")
    else:
        meas = pp.get("measured_bubble_fraction")
        ana = pp.get("analytic_bubble_fraction")
        drift = (
            None if meas is None or ana is None else round(meas - ana, 4)
        )
        lines += [
            f"Schedule **{pp.get('schedule')}** on {pp.get('n_stages')} "
            f"stage(s), {pp.get('microbatches')} microbatch(es), "
            f"virtual {pp.get('virtual')}: measured bubble "
            f"{_pct(meas)} vs analytic {_pct(ana)}"
            + ("" if drift is None else f" (drift {drift:+.4f})")
            + ".",
            "",
            "| stage | " + " | ".join(PHASE_COLUMNS)
            + " | useful slots | bubble |",
            "|---:|" + "---:|" * (len(PHASE_COLUMNS) + 2),
        ]
        for st in pp.get("per_stage", []):
            cols = " | ".join(str(st.get(c, 0)) for c in PHASE_COLUMNS)
            lines.append(
                f"| {st.get('stage')} | {cols} | {st.get('useful_slots')}"
                f" | {_pct(st.get('bubble_fraction'))} |"
            )
        if meas is not None and ana is not None and abs(drift) > 1e-9:
            lines += ["", "Measured and analytic bubbles DISAGREE — the "
                          "compiled schedule did not execute the tick "
                          "table the factory accounted for."]
    lines.append("")

    # -- Restarts -----------------------------------------------------
    if a["restarts"]:
        lines += ["## Restarts", ""]
        for r in a["restarts"]:
            lines.append(
                f"- `{r['kind']}` attempt {r['attempt']} "
                f"(failed: {r['failed']})"
            )
        lines.append("")

    # -- Elasticity ---------------------------------------------------
    lines += ["## Elasticity", ""]
    el = a["elasticity"]
    if el is None:
        lines.append("No membership events — a fixed-size gang (run with "
                     "`--elastic` to resize the mesh around worker loss "
                     "instead of restarting).")
    else:
        lines += [
            f"**{el['n_resizes']} resize(s)** across "
            f"{len(el['epochs'])} membership epoch(s), "
            f"{el['resize_downtime_s']:.2f}s total resize downtime.",
            "",
            "| epoch | size | roster |",
            "|---:|---:|---|",
        ]
        for ep in el["epochs"]:
            roster = ", ".join(str(m) for m in ep["roster"]) or "—"
            lines.append(f"| {ep['epoch']} | {ep['size']} | {roster} |")
        if el["resizes"]:
            down = {d["epoch"]: d["seconds"] for d in el["downtimes"]}
            lines += [
                "",
                "| epoch | resize | left | joined | downtime |",
                "|---:|---|---|---|---:|",
            ]
            for rz in el["resizes"]:
                d = down.get(rz["epoch"])
                lines.append(
                    f"| {rz['epoch']} | {rz['old_size']} -> "
                    f"{rz['new_size']} | "
                    f"{', '.join(rz['left']) or '—'} | "
                    f"{', '.join(rz['joined']) or '—'} | "
                    f"{'-' if d is None else f'{d:.2f}s'} |"
                )
        if el.get("suspects"):
            # Several survivors may flag the same member; collapse to
            # one line per suspect with the worst observed age.
            worst: dict = {}
            for s in el["suspects"]:
                m = s.get("member")
                if m not in worst or (s.get("age_s") or 0) > (
                    worst[m].get("age_s") or 0
                ):
                    worst[m] = s
            lines += [""] + [
                f"- suspect `{m}` (heartbeat age "
                f"{worst[m].get('age_s'):.2f}s, epoch "
                f"{worst[m].get('epoch')}) — hysteresis window, not yet "
                "tombstoned"
                for m in sorted(worst)
            ]
        if el.get("rehosts"):
            lines += [""] + [
                f"- rendezvous store re-hosted at generation "
                f"{rh['generation']} on `{rh['owner']}`"
                for rh in el["rehosts"]
            ]
        if el.get("verdict"):
            v = el["verdict"]
            fault = v["fault"] or "no injected fault"
            lines += [
                "",
                f"**Verdict: `{v['rung']}` rung** "
                f"(degradation ladder: resize -> checkpoint restart -> "
                f"loud fail), attributed to {fault}.",
            ]
        if el["restart_reclaimed_s"] is not None:
            lines += [
                "",
                f"Restart-seconds reclaimed: **"
                f"{el['restart_reclaimed_s']:.2f}s** vs this run's own "
                "mean restart gap.",
            ]
        elif el["downtimes"]:
            lines += [
                "",
                "No cold restarts in this timeline to reclaim against — "
                "bench.py's `elastic_resize` section measures resize vs "
                "supervised restart head-to-head.",
            ]
    lines.append("")

    # -- Integrity ----------------------------------------------------
    ig = a["integrity"]
    if ig is not None:
        lines += ["## Integrity", ""]
        lines.append(
            f"**{ig['checks']} digest check(s)**, "
            f"{len(ig['detects'])} mismatch(es), "
            f"{len(ig['evictions'])} eviction(s)."
        )
        if ig["detects"]:
            lines += [
                "",
                "| step | rank(s) | method | leaves |",
                "|---:|---|---|---|",
            ]
            for d in ig["detects"]:
                ranks = ", ".join(str(x) for x in d["ranks"]) or (
                    "transient" if d["rank"] == -1 else str(d["rank"])
                )
                lines.append(
                    f"| {d['step']} | {ranks} | {d['method']} | "
                    f"{', '.join(d['leaves']) or '—'} |"
                )
        if ig["evictions"]:
            ev = ", ".join(
                f"rank {e['rank']} @ step {e['step']}"
                for e in ig["evictions"]
            )
            lines += ["", f"Evicted via elastic resize: {ev}."]
        lines.append("")

    # -- Alerts -------------------------------------------------------
    lines += ["## Alerts", ""]
    if not a["alerts"]:
        if a["run_summary"] is not None:
            # run_summary proves the run is new enough to have alerting;
            # silence genuinely means nothing fired.
            lines.append("No alerts fired.")
        else:
            lines.append("No `alert` events — this run predates alerting "
                         "or ran without `--alerts`.")
    else:
        by_rule: dict[str, list[dict]] = {}
        for al in a["alerts"]:
            by_rule.setdefault(str(al["rule"]), []).append(al)
        lines += [
            f"**{len(a['alerts'])} alert(s)** across "
            f"{len(by_rule)} rule(s):",
            "",
            "| rule | count | first (step) | last (step) |",
            "|---|---:|---:|---:|",
        ]
        for rule, als in sorted(by_rule.items()):
            lines.append(
                f"| {rule} | {len(als)} | {als[0].get('step')} | "
                f"{als[-1].get('step')} |"
            )
    lines.append("")

    # -- Lint ---------------------------------------------------------
    lines += ["## Lint", ""]
    if not a["lint"]:
        lines.append("No `lint_report` events — run "
                     "`python scripts/ddplint.py --events-dir DIR` (or "
                     "`dpp.py --lint-step`) to record static-analysis "
                     "health next to the runtime telemetry.")
    else:
        total = sum(l["n_findings"] or 0 for l in a["lint"])
        verdict = "clean" if total == 0 else f"**{total} finding(s)**"
        lines += [
            f"Static analysis {verdict} across "
            f"{len(a['lint'])} layer(s):",
            "",
            "| layer | findings | rules |",
            "|---|---:|---|",
        ]
        for l in a["lint"]:
            rules = ", ".join(l["rules"] or []) or "—"
            lines.append(
                f"| {l['layer']} | {l['n_findings']} | {rules} |"
            )
        for l in a["lint"]:
            for f in l["findings"]:
                lines += ["", f"- `{f}`"]
    lines.append("")

    # -- Protocol -----------------------------------------------------
    lines += ["## Protocol", ""]
    conf = a.get("conformance") or []
    if not conf:
        lines.append(
            "Timeline conforms to the declared protocol specs "
            "(rendezvous membership, request lifecycle, handoff NAK "
            "budget — `analysis.protocol`): no PL405 violations."
        )
    else:
        lines += [
            f"**{len(conf)} PL405 violation(s)** — the recorded "
            "timeline contradicts the declared protocol state "
            "machines:",
            "",
        ]
        lines += [f"- `{f}`" for f in conf]
    lines.append("")

    # -- Serving ------------------------------------------------------
    lines += ["## Serving", ""]
    sv = a["serving"]
    if sv is None:
        lines.append("No serving events — a training-only run (serve "
                     "with `python scripts/ddp_serve.py --events-dir "
                     "DIR` to record the request lifecycle).")
    else:
        tok_s = "-" if sv["tok_s"] is None else f"{sv['tok_s']:.1f}"
        p50 = sv["ttft_p50_s"]
        p99 = sv["ttft_p99_s"]
        lines += [
            f"**{sv['completed']}/{sv['admitted']} requests completed**, "
            f"{sv['tokens_out']} tokens out at {tok_s} tok/s "
            f"(event-span clock).",
            "",
            "| metric | value |",
            "|---|---:|",
            f"| TTFT p50 | {'-' if p50 is None else f'{p50 * 1e3:.1f} ms'} |",
            f"| TTFT p99 | {'-' if p99 is None else f'{p99 * 1e3:.1f} ms'} |",
            f"| decode steps | {sv['decode_steps']} |",
            f"| mean active slots | {sv['mean_active']:.2f} |",
            f"| max active slots | {sv['active_max']} |",
            f"| prefill chunks | {sv['prefill_chunks']} |",
            f"| LRU evictions | {sv['evictions'].get('lru', 0)} |",
            f"| preempt evictions | {sv['evictions'].get('preempt', 0)} |",
            f"| blocks reclaimed | {sv['evicted_blocks']} |",
        ]
        if sv["prefix_hits"]:
            hit = sv["prefix_hit_frac"]
            avoided = sv["prefill_flops_avoided_frac"]
            lines += [
                f"| prefix-cache hits | {sv['prefix_hits']} "
                f"({'-' if hit is None else f'{hit:.0%}'} of admits) |",
                f"| prefill FLOPs avoided | "
                f"{'-' if avoided is None else f'{avoided:.0%}'} "
                f"({sv['prefix_hit_tokens']} cached ctx tokens) |",
            ]
        if sv["spec_dispatches"]:
            lines += [
                f"| spec-verify dispatches | {sv['spec_dispatches']} |",
                f"| spec tokens drafted / accepted | "
                f"{sv['spec_drafted']} / {sv['spec_accepted']} |",
                f"| mean accepted tokens per row | "
                f"{sv['spec_accept_mean']:.2f} |",
            ]
            hist = " ".join(
                f"{k}:{v}" for k, v in sv["accept_hist"].items()
            )
            lines += [
                "",
                f"Accept-length histogram (dispatch mean, tokens/row): "
                f"`{hist}`",
            ]
    lines.append("")

    # -- Serving fleet ------------------------------------------------
    fl = a["fleet"]
    if fl is not None:
        lines += ["## Serving fleet", ""]
        aff = fl["affinity_frac"]
        ho_mean = fl["handoff_s_mean"]
        ho_p99 = fl["handoff_s_p99"]
        lines += [
            f"**{fl['routed']} requests routed**, "
            f"{fl['affinity_hits']} session-affinity hits "
            f"({'-' if aff is None else f'{aff:.0%}'}), "
            f"{fl['handoffs']} prefill->decode KV handoffs "
            f"({fl['handoff_bytes']} bytes).",
            "",
            "| metric | value |",
            "|---|---:|",
            f"| handoff mean | "
            f"{'-' if ho_mean is None else f'{ho_mean * 1e3:.1f} ms'} |",
            f"| handoff p99 | "
            f"{'-' if ho_p99 is None else f'{ho_p99 * 1e3:.1f} ms'} |",
            f"| re-delivered handoffs | {fl['redelivered']} |",
            f"| router queue depth max | {fl['queue_depth_max']} |",
        ]
        for tier in sorted(fl["tiers"]):
            t = fl["tiers"][tier]
            p50 = t.get("p50_ttft_s")
            p99 = t.get("p99_ttft_s")
            lines.append(
                f"| {tier} tier | {t.get('completed', 0)} done, "
                f"TTFT p50 "
                f"{'-' if p50 is None else f'{p50 * 1e3:.1f} ms'} / p99 "
                f"{'-' if p99 is None else f'{p99 * 1e3:.1f} ms'} |"
            )
        for v in fl["verdicts"]:
            lines.append(
                f"| engine verdict | `{v.get('engine')}` -> "
                f"**{v.get('rung')}** ({v.get('tier')} tier, "
                f"{v.get('requeued', 0)} requeued, "
                f"{v.get('reason')}) |"
            )
        lines.append("")

    # -- TTFT decomposition -------------------------------------------
    td = a["ttft_decomposition"]
    if td is not None:
        lines += ["## TTFT decomposition", ""]
        err = td.get("ttft_decomp_err_frac")
        lines += [
            f"**{td['requests']} traced request(s)** — span-tree "
            "accounting of each first-token latency "
            f"(worst self-consistency error "
            f"{'-' if err is None else f'{err:.1%}'}; gate ≤ 5%).",
            "",
            "| segment | share of TTFT | p50 | p99 |",
            "|---|---:|---:|---:|",
        ]
        for seg in ("queue", "prefill", "handoff", "decode"):
            share = td.get(f"ttft_{seg}_share_frac")
            p50 = td.get(f"{seg}_p50_s")
            p99 = td.get(f"{seg}_p99_s")
            lines.append(
                f"| {seg} | {'-' if share is None else f'{share:.1%}'} | "
                f"{'-' if p50 is None else f'{p50 * 1e3:.1f} ms'} | "
                f"{'-' if p99 is None else f'{p99 * 1e3:.1f} ms'} |"
            )
        for tier, roll in sorted((td.get("tiers") or {}).items()):
            if not roll.get("requests"):
                continue
            q = roll.get("ttft_queue_share_frac")
            lines.append(
                f"| {tier}-tier rollup | {roll['requests']} request(s), "
                f"queue share {'-' if q is None else f'{q:.1%}'} | | |"
            )
        problems = td.get("lineage_problems") or []
        if problems:
            lines += [""] + [
                f"- **lineage problem**: {p}" for p in problems[:5]
            ]
        lines.append("")

    # -- Tuning -------------------------------------------------------
    lines += ["## Tuning", ""]
    tu = a["tuning"]
    if tu is None:
        lines.append("No tune_* events — search with `dpp.py --autotune "
                     "search` (or `python scripts/ddp_tune.py search "
                     "--events-dir DIR`) to record trials here.")
    else:
        res = tu["result"]
        if res:
            gain = res.get("gain_frac")
            lines += [
                f"**autotune {res.get('mode')}**: winner "
                f"`{res.get('winner')}`"
                + (f", gain {gain * 100:+.1f}% vs baseline"
                   if isinstance(gain, (int, float)) else "")
                + ("" if res.get("applied") in (None, True)
                   else " — **NOT applied** (key mismatch, ran with CLI "
                        "defaults)")
                + ".",
                "",
            ]
        if tu["trials"]:
            lines += [
                "| trial | status | predicted | measured | drift | "
                "warm |",
                "|---|---|---:|---:|---:|---|",
            ]
            fmt = lambda v: (  # noqa: E731
                "-" if not isinstance(v, (int, float))
                else f"{v * 1e3:.1f} ms"
            )
            for t in tu["trials"]:
                d = t.get("drift_frac")
                lines.append(
                    f"| `{t['trial']}` | {t['status']} "
                    f"| {fmt(t.get('predicted_step_s'))} "
                    f"| {fmt(t.get('measured_step_s'))} "
                    f"| {'-' if not isinstance(d, (int, float)) else f'{d * 100:+.0f}%'} "
                    f"| {t.get('warm_mode') or '-'} |"
                )
            drifts = tu["drift_fracs"]
            if drifts:
                # the search doubles as a cost-model calibration probe:
                # consistent positive drift = the efficiency constant is
                # too optimistic for this backend, not a tuner bug
                mean = sum(drifts) / len(drifts)
                worst = max(drifts, key=abs)
                lines += [
                    "",
                    f"Cost-model drift over {len(drifts)} measured "
                    f"trial(s): mean {mean * 100:+.0f}%, worst "
                    f"{worst * 100:+.0f}% "
                    "((measured - predicted) / predicted).",
                ]
    lines.append("")

    # -- Run summary + trace ------------------------------------------
    rs = a["run_summary"]
    if rs:
        lines += ["## Run summary", ""]
        shown = ("windows", "steps_total", "mfu_mean", "step_s_p50",
                 "step_s_p99", "live_hwm_bytes", "goodput", "restarts",
                 "alerts_total", "status")
        parts = [f"{k} `{rs[k]}`" for k in shown if rs.get(k) is not None]
        lines += [", ".join(parts) + ".", "",
                  "Gate this run against a baseline with "
                  f"`python scripts/perf_gate.py {events_dir} "
                  "--store RUNS_DIR --baseline NAME`.", ""]
    lines += [
        "## Trace",
        "",
        "Export this timeline for https://ui.perfetto.dev with "
        f"`python scripts/ddp_trace.py {events_dir}` "
        "(per-rank tracks, mfu/step_s/memory counters, "
        "restart/nan/alert marks).",
        "",
    ]
    return "\n".join(lines) + "\n"


def main(argv: list[str] | None = None) -> int:
    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.add_argument("events_dir", help="directory holding events-*.jsonl / "
                                       "timeline.jsonl")
    ap.add_argument("--json", action="store_true",
                    help="emit the analysis as JSON instead of markdown")
    ap.add_argument("-o", "--out", default=None,
                    help="write the report here instead of stdout")
    args = ap.parse_args(argv)

    if not os.path.isdir(args.events_dir):
        print(f"ddp_report: no such directory: {args.events_dir}",
              file=sys.stderr)
        return 1
    records = load_timeline(args.events_dir)
    analysis = analyze(records)
    text = (
        json.dumps(analysis, indent=2) + "\n" if args.json
        else render_markdown(analysis, args.events_dir)
    )
    if args.out:
        with open(args.out, "w") as fh:
            fh.write(text)
    else:
        sys.stdout.write(text)
    return 0 if records else 1


if __name__ == "__main__":
    raise SystemExit(main())
