#!/usr/bin/env python
"""ddplint — static SPMD-invariant checker for the DDP reproduction.

Layers (rule table: ``--list-rules``; registry in
``distributeddataparallel_tpu/analysis/rules.py``):

  --ast      AST rules over the package source, dpp.py, and scripts/
             (AL1xx: the train-step pass in ``ast_rules`` plus the
             concurrency/clock pass in ``sync_lint``).  Stdlib-only:
             runs in any interpreter, no jax import.
  --graph    Graph rules over the *traced/lowered* train steps of the
             repo's own factories, exercised on tiny CPU-sized configs.
             Traces and lowers but never compiles, so it is fast and
             CPU-safe (forces JAX_PLATFORMS=cpu + 8 host devices).
             This layer also runs the sharding-flow pass (SF2xx) over
             each lowered module and — for steps that attach a schedule
             IR (pipeline stages, bucketed grad-sync) — the
             schedule-as-data lint (SL3xx).
  --protocol Protocol rules (PL4xx): the small-scope model checker
             exhaustively explores the declared rendezvous / router /
             handoff / allocator state machines (2–4 actors, >=1
             fault) — invariant violations arrive with a minimal
             counterexample trace.  Stdlib-only, sub-second.

With no layer flag, all three layers run.  ``--changed-only`` narrows
the AST layer to files in ``git diff --name-only HEAD``, skips the
graph layer unless step-defining code changed, and skips the protocol
layer unless analysis/runtime/serving code changed — the fast local
pre-push mode.
``--events-dir DIR`` additionally writes one schema-valid
``lint_report`` event per layer to ``DIR/events-lint.jsonl`` so run
reports can show lint health next to runtime telemetry.

Exit status: 0 clean, 1 findings, 2 operational error (including a
checker emitting a rule id the registry doesn't know).

Examples:
    python scripts/ddplint.py --graph --ast       # what CI runs
    python scripts/ddplint.py --ast --changed-only
    python scripts/ddplint.py --graph --modes all # adds fsdp, pp, serve
    python scripts/ddplint.py --graph --modes serve  # inference engine
"""

from __future__ import annotations

import argparse
import os
import subprocess
import sys
from pathlib import Path

ROOT = Path(__file__).resolve().parents[1]
sys.path.insert(0, str(ROOT))

#: a graph-layer run is warranted when any of these changed
_GRAPH_TRIGGERS = (
    "distributeddataparallel_tpu/analysis/",
    "distributeddataparallel_tpu/parallel/",
    "distributeddataparallel_tpu/training/",
    "distributeddataparallel_tpu/ops/",
    "dpp.py",
)

#: a protocol-layer run is warranted when the specs or the live modules
#: they model changed
_PROTOCOL_TRIGGERS = (
    "distributeddataparallel_tpu/analysis/",
    "distributeddataparallel_tpu/runtime/",
    "distributeddataparallel_tpu/serving/",
)

#: graph-lint driver modes; "all" expands to every key
DEFAULT_MODES = ("dp", "zero", "bucket", "bf16")
ALL_MODES = ("dp", "zero", "bucket", "bf16", "fsdp", "pp", "serve")


def _ensure_cpu() -> None:
    """Make tracing CPU-safe with a multi-device mesh BEFORE jax loads.

    Must run before the first jax import: device count is fixed at
    backend init (jax 0.4.x has no jax_num_cpu_devices config), so if
    jax is already in, we trust the host process set things up.
    """
    if "jax" in sys.modules:
        return
    os.environ.setdefault("JAX_PLATFORMS", "cpu")
    flags = os.environ.get("XLA_FLAGS", "")
    if "xla_force_host_platform_device_count" not in flags:
        os.environ["XLA_FLAGS"] = (
            flags + " --xla_force_host_platform_device_count=8"
        ).strip()


def _changed_files(root: Path | None = None) -> list[str]:
    out = subprocess.run(
        ["git", "diff", "--name-only", "HEAD"],
        cwd=root or ROOT, capture_output=True, text=True, check=True,
    ).stdout
    return [l.strip() for l in out.splitlines() if l.strip()]


def run_ast(changed_only: bool, *, root: Path | None = None) -> list:
    from distributeddataparallel_tpu.analysis import ast_rules, sync_lint

    root = root or ROOT
    targets = ast_rules.default_targets(root)
    if changed_only:
        changed = set(_changed_files(root))
        targets = [
            t for t in targets
            if t.relative_to(root).as_posix() in changed
        ]
        if not targets:
            return []
    return (ast_rules.lint_paths(targets, root)
            + sync_lint.lint_paths(targets, root))


def run_protocol(*, verbose: bool = True) -> list:
    """Exhaustively explore every shipped protocol spec (PL4xx)."""
    from distributeddataparallel_tpu.analysis import protocol

    findings: list = []
    for rep in protocol.explore_all():
        findings += rep.findings
        if verbose:
            status = "ok" if rep.ok else f"{len(rep.findings)} finding(s)"
            print(
                f"ddplint proto [{rep.spec}] {status} "
                f"states={rep.n_states} moves={rep.n_moves} "
                f"complete={rep.complete}"
            )
    return findings


def _graph_cases(modes):
    """Yield (mode, step, state, batch, rng) on tiny configs — small
    enough that every trace is sub-second on CPU."""
    import jax
    import jax.numpy as jnp
    import numpy as np
    import optax

    import distributeddataparallel_tpu as ddp
    from distributeddataparallel_tpu.data.loader import shard_batch
    from distributeddataparallel_tpu.models.simple_cnn import TinyMLP
    from distributeddataparallel_tpu.ops.losses import cross_entropy_loss
    from distributeddataparallel_tpu.training.train_step import (
        make_train_step,
    )

    rng = jax.random.PRNGKey(0)
    mesh = ddp.make_mesh(("data",))
    model = TinyMLP(features=(32,), num_classes=10)
    params = model.init(jax.random.PRNGKey(0), jnp.zeros((1, 8)))["params"]

    def loss_fn(params, batch, _rng):
        logits = model.apply({"params": params}, batch["image"])
        return cross_entropy_loss(logits, batch["label"]), {}

    def mlp_state(p):
        return ddp.TrainState.create(
            apply_fn=model.apply, params=p, tx=optax.sgd(0.1)
        )

    batch = {
        "image": jnp.zeros((8, 8)),
        "label": jnp.zeros((8,), jnp.int32),
    }
    factory_kw = {
        "dp": {},
        "zero": {"zero": True},
        "bucket": {"bucket_bytes": 1 << 20},
    }
    for mode in ("dp", "zero", "bucket"):
        if mode in modes:
            step = make_train_step(loss_fn, mesh=mesh, **factory_kw[mode])
            yield mode, step, mlp_state(params), batch, rng
    if "bf16" in modes:
        bf16 = jax.tree.map(
            lambda x: x.astype(jnp.bfloat16), params
        )
        step = make_train_step(loss_fn, mesh=mesh)
        yield "bf16", step, mlp_state(bf16), batch, rng

    if not ({"fsdp", "pp", "serve"} & set(modes)):
        return
    from distributeddataparallel_tpu.models import TransformerLM, tiny_lm

    nprng = np.random.default_rng(0)
    if "fsdp" in modes:
        from distributeddataparallel_tpu.parallel.fsdp import (
            fsdp_state,
            make_fsdp_train_step,
        )

        cfg = tiny_lm(
            num_layers=2, num_heads=2, d_model=32, d_ff=64,
            max_seq_len=32, scan_layers=True,
        )
        p = TransformerLM(cfg).init(
            jax.random.PRNGKey(0), jnp.zeros((1, 16), jnp.int32)
        )["params"]
        st = fsdp_state(cfg, p, optax.adam(1e-2), mesh)
        b = shard_batch(
            {"tokens": nprng.integers(
                0, 256, size=(8, 17)).astype(np.int32)},
            mesh,
        )
        yield "fsdp", make_fsdp_train_step(cfg, mesh=mesh), st, b, rng
    if "pp" in modes:
        from distributeddataparallel_tpu.parallel import (
            make_pp_train_step,
            shard_state_pp,
        )

        mesh2 = ddp.make_mesh(("data", "pipe"), shape=(2, 4))
        cfg = tiny_lm(
            num_layers=4, num_heads=2, d_model=32, d_ff=64,
            max_seq_len=32, scan_layers=True,
        )
        p = TransformerLM(cfg).init(
            jax.random.PRNGKey(0), jnp.zeros((1, 32), jnp.int32)
        )["params"]
        st = shard_state_pp(
            ddp.TrainState.create(
                apply_fn=None, params=p, tx=optax.adam(1e-2)
            ),
            mesh2,
        )
        b = shard_batch(
            {"tokens": nprng.integers(
                0, 256, size=(8, 33)).astype(np.int32)},
            mesh2,
        )
        step = make_pp_train_step(cfg, mesh=mesh2, microbatches=2)
        yield "pp", step, st, b, rng

    if "serve" in modes:
        from typing import Any

        import flax.struct

        from distributeddataparallel_tpu.analysis.rules import (
            collective_manifest,
        )
        from distributeddataparallel_tpu.serving import (
            EngineConfig,
            InferenceEngine,
        )

        cfg = tiny_lm(
            num_layers=2, num_heads=2, d_model=32, d_ff=64,
            max_seq_len=32,
        )
        lm = TransformerLM(cfg)
        p = lm.init(
            jax.random.PRNGKey(0), jnp.zeros((1, 8), jnp.int32)
        )["params"]
        engine = InferenceEngine(
            lm, p,
            EngineConfig(num_slots=4, num_blocks=8, block_size=8,
                         prefill_chunk=8),
        )

        # The decode program adapted to the linter's (state, batch,
        # rng) contract: state.params is the KV POOL — the buffer set
        # the manifest's donate=True makes GL003 verify is aliased
        # input->output in the lowered module (a lost pool donation
        # doubles serving memory every step).  grad_reduce={} asserts
        # the inference step carries NO training collectives on any
        # axis — a psum leaking in through a shared model path would
        # wedge a serving replica that has no gang to sync with.
        @flax.struct.dataclass
        class ServeState:
            params: Any
            opt_state: Any

        bps = engine.blocks_per_seq
        sbatch = {
            "tables": jnp.zeros((4, bps), jnp.int32),
            "toks": jnp.zeros((4, 1), jnp.int32),
            "pos": jnp.zeros((4,), jnp.int32),
        }

        def serve_step(state, batch, _rng, _eng=engine):
            return _eng._decode_prog(
                _eng.params, state.params, batch["tables"],
                batch["toks"], batch["pos"],
            )

        serve_step.lower = (
            lambda state, batch, _rng, _eng=engine: _eng._decode_prog.lower(
                _eng.params, state.params, batch["tables"],
                batch["toks"], batch["pos"],
            )
        )
        serve_step.collective_manifest = collective_manifest(
            "serve", grad_reduce={}, donate=True,
        )
        yield ("serve", serve_step,
               ServeState(params=engine.pool, opt_state=()), sbatch, rng)

        # The speculative-verify program ((num_slots, k+1) window) must
        # hold the exact same contract as decode: zero training
        # collectives and full pool donation (GL003) — it replaces the
        # decode program on the hot path whenever spec_k > 0.
        vengine = InferenceEngine(
            lm, p,
            EngineConfig(num_slots=4, num_blocks=8, block_size=8,
                         prefill_chunk=8, spec_k=3),
        )
        vbatch = {
            "tables": jnp.zeros((4, bps), jnp.int32),
            "toks": jnp.zeros((4, 4), jnp.int32),
            "pos": jnp.zeros((4,), jnp.int32),
        }

        def verify_step(state, batch, _rng, _eng=vengine):
            return _eng._verify_prog(
                _eng.params, state.params, batch["tables"],
                batch["toks"], batch["pos"],
            )

        verify_step.lower = (
            lambda state, batch, _rng, _eng=vengine:
            _eng._verify_prog.lower(
                _eng.params, state.params, batch["tables"],
                batch["toks"], batch["pos"],
            )
        )
        verify_step.collective_manifest = collective_manifest(
            "serve-verify", grad_reduce={}, donate=True,
        )
        yield ("serve-verify", verify_step,
               ServeState(params=vengine.pool, opt_state=()), vbatch, rng)


def _schedule_ir_of(step, state):
    """The schedule IR a step carries as data: pipeline factories attach
    ``.schedule_ir`` directly; bucketed grad-sync steps attach a
    ``.comm_schedule`` builder keyed on the param tree."""
    ir = getattr(step, "schedule_ir", None)
    if ir is None and getattr(step, "comm_schedule", None) is not None:
        ir = step.comm_schedule(state.params)
    return ir


def run_graph(modes, *, verbose: bool = True) -> dict:
    """Trace/lower every requested factory config and run the graph
    (GL0xx), sharding-flow (SF2xx), and schedule (SL3xx) passes.
    Returns findings per layer: {"graph": [...], "flow": [...],
    "schedule": [...]}."""
    _ensure_cpu()
    from distributeddataparallel_tpu.analysis import (
        schedule_lint,
        shard_flow,
    )
    from distributeddataparallel_tpu.analysis.graph_lint import (
        lint_train_step,
    )
    from distributeddataparallel_tpu.observability.memory import (
        hbm_budget_bytes,
    )

    budget = hbm_budget_bytes()
    by_layer: dict[str, list] = {"graph": [], "flow": [], "schedule": []}
    for mode, step, state, batch, rng in _graph_cases(modes):
        rep = lint_train_step(step, state, batch, rng, mode=mode)
        by_layer["graph"] += rep.findings

        flow = shard_flow.analyze_step(
            step, state, batch, rng, mode=mode, hbm_budget_bytes=budget,
        )
        by_layer["flow"] += flow.findings

        ir = _schedule_ir_of(step, state)
        sched = []
        if ir is not None:
            hops = sum(
                c.effective_count for c in (rep.collectives or [])
                if c.prim == ir.hop_prim and ir.hop_axis in c.axes
                and c.nonscalar
            )
            sched = schedule_lint.lint_schedule(
                ir,
                manifest=getattr(step, "collective_manifest", None),
                traced_hops=hops,
                bubble=getattr(step, "bubble_accounting", None),
                where=f"sched:{mode}:{ir.kind}",
            )
            by_layer["schedule"] += sched

        if verbose:
            counts = " ".join(
                f"{k}={v}" for k, v in sorted(rep.collective_counts.items())
            )
            donate = (
                f" donated={rep.donated_args}/{rep.donation_expected}"
                if rep.donated_args is not None else ""
            )
            status = "ok" if rep.ok else f"{len(rep.findings)} finding(s)"
            print(
                f"ddplint graph [{mode}] {status} "
                f"fp={rep.fingerprint} {counts}{donate}"
            )
            n_bad = len(flow.findings) + len(sched)
            sched_note = f" schedule={ir.kind}" if ir is not None else ""
            print(
                f"ddplint flow  [{mode}] "
                f"{'ok' if not n_bad else f'{n_bad} finding(s)'} "
                f"collectives={len(flow.collectives)}{sched_note}"
            )
    return by_layer


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(
        prog="ddplint",
        description=__doc__,
        formatter_class=argparse.RawDescriptionHelpFormatter,
    )
    ap.add_argument("--ast", action="store_true",
                    help="run the AST layer (AL1xx rules)")
    ap.add_argument("--graph", action="store_true",
                    help="run the graph layer (GL0xx rules)")
    ap.add_argument("--protocol", action="store_true",
                    help="run the protocol layer (PL4xx rules): "
                         "model-check the declared rendezvous/router/"
                         "handoff/allocator state machines")
    ap.add_argument("--changed-only", action="store_true",
                    help="lint only files changed vs HEAD; skip the "
                         "graph layer unless step code changed")
    ap.add_argument("--modes", default=",".join(DEFAULT_MODES),
                    help="graph-lint configurations, comma-separated "
                         f"(default: %(default)s; 'all' = "
                         f"{','.join(ALL_MODES)})")
    ap.add_argument("--list-rules", action="store_true",
                    help="print the rule table and exit")
    ap.add_argument("--events-dir", metavar="DIR",
                    help="append one lint_report event per layer to "
                         "DIR/events-lint.jsonl")
    args = ap.parse_args(argv)

    from distributeddataparallel_tpu.analysis.rules import (
        format_findings,
        rule_table,
        unregistered_rule_ids,
    )

    if args.list_rules:
        print(rule_table())
        return 0

    any_layer = args.ast or args.graph or args.protocol
    do_ast = args.ast or not any_layer
    do_graph = args.graph or not any_layer
    do_protocol = args.protocol or not any_layer
    modes = ALL_MODES if args.modes == "all" else tuple(
        m.strip() for m in args.modes.split(",") if m.strip()
    )
    unknown = set(modes) - set(ALL_MODES)
    if unknown:
        ap.error(f"unknown --modes {sorted(unknown)}; pick from "
                 f"{','.join(ALL_MODES)} or 'all'")

    by_layer: dict[str, list] = {}
    if do_ast:
        by_layer["ast"] = run_ast(args.changed_only)
    if do_protocol:
        if args.changed_only and not any(
            c.startswith(_PROTOCOL_TRIGGERS) for c in _changed_files()
        ):
            print("ddplint proto: skipped (no protocol-adjacent changes)")
        else:
            by_layer["protocol"] = run_protocol()
    if do_graph:
        if args.changed_only and not any(
            c.startswith(_GRAPH_TRIGGERS) for c in _changed_files()
        ):
            print("ddplint graph: skipped (no step-defining changes)")
        else:
            by_layer.update(run_graph(modes))

    findings = [f for fs in by_layer.values() for f in fs]

    if args.events_dir:
        from distributeddataparallel_tpu.observability.events import (
            EventLog,
        )

        path = os.path.join(args.events_dir, "events-lint.jsonl")
        with EventLog(path, proc="lint") as ev:
            for layer, fs in sorted(by_layer.items()):
                ev.emit(
                    "lint_report",
                    layer=layer,
                    n_findings=len(fs),
                    rules=sorted({f.rule for f in fs}),
                    findings=[str(f) for f in fs[:50]],
                )

    # A checker inventing a rule id is an operational error, not a
    # finding: CI must hard-fail rather than report it alongside lint.
    bad_ids = unregistered_rule_ids(findings)
    if bad_ids:
        print(f"ddplint: unregistered rule id(s) {bad_ids} — register "
              "them in analysis/rules.py RULES", file=sys.stderr)
        return 2

    if findings:
        print(format_findings(findings), file=sys.stderr)
        print(f"ddplint: {len(findings)} finding(s)", file=sys.stderr)
        return 1
    print("ddplint: clean")
    return 0


if __name__ == "__main__":
    _ensure_cpu()
    sys.exit(main())
