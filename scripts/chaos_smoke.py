#!/usr/bin/env python
"""CI gate: multi-host chaos smoke — real processes, real faults.

Two supervised 3-process gangs on a TCP rendezvous store
(``runtime.hostgang``), each with one injected fault, each required to
end on the resize rung of the degradation ladder with the fault named
in the supervisor's ``gang_verdict``:

- ``host-kill``: one host dies abruptly; the survivors tombstone it and
  absorb the loss in place (zero respawns).
- ``rdzv-kill``: the rendezvous server dies; the elected smallest-name
  survivor re-hosts the store (``rdzv_rehost``) and the intact roster
  finishes.

Must run as a file (not ``python -``): the workers are spawned
processes, and multiprocessing re-imports ``__main__`` from its path.
"""

import json
import os
import sys
import tempfile

os.environ.setdefault("JAX_PLATFORMS", "cpu")
sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from distributeddataparallel_tpu.runtime.hostgang import hostgang_worker
from distributeddataparallel_tpu.runtime.launcher import spawn


def run(base: str, name: str, chaos: str) -> list[dict]:
    root = os.path.join(base, name)
    events = os.path.join(root, "events")
    os.makedirs(events)
    cfg = {"store_root": root, "world_size": 3, "steps": 8,
           "step_s": 0.05, "transport": "tcp", "min_size": 1,
           "heartbeat_timeout_s": 2.5, "suspect_after_s": 1.0}
    spawn(hostgang_worker, args=(cfg,), nprocs=3, max_restarts=2,
          restart_backoff_s=0.1, env={"DDP_CHAOS": chaos},
          events_dir=events, elastic_store=os.path.join(root, "store"),
          min_procs=1)
    recs = []
    for fn in sorted(os.listdir(events)):
        if fn.endswith(".jsonl") and fn != "timeline.jsonl":
            with open(os.path.join(events, fn)) as fh:
                recs += [json.loads(ln) for ln in fh if ln.strip()]
    return recs


def main() -> None:
    base = sys.argv[1] if len(sys.argv) > 1 else tempfile.mkdtemp(
        prefix="chaos-smoke-"
    )

    recs = run(base, "hostkill", "host-kill@3:1")
    v = [r for r in recs if r["kind"] == "gang_verdict"]
    assert len(v) == 1 and v[0]["rung"] == "resize", v
    assert v[0]["fault_kind"] == "host-kill" and v[0]["respawns"] == 0, v
    print("host-kill: resize rung, fault attributed, 0 respawns")

    recs = run(base, "rdzvkill", "rdzv-kill@3")
    v = [r for r in recs if r["kind"] == "gang_verdict"]
    assert len(v) == 1 and v[0]["rung"] == "resize", v
    assert any(r["kind"] == "rdzv_rehost" for r in recs), "no re-host event"
    print("rdzv-kill: store re-hosted, resize rung")


if __name__ == "__main__":
    main()
