#!/usr/bin/env bash
# CI gate: tier-1 tests + static lint + schema sync, in fail-fast order.
#
#   scripts/ci.sh          # full gate (what the merge queue runs)
#   scripts/ci.sh --fast   # skip the pytest tier, keep the static gates
#
# Order is cheapest-first so drift fails in seconds:
#   1. ddplint --ast            AST rules (host-sync, broad-except,
#                               unregistered emit kinds, plus the
#                               sync_lint AL105-AL108 concurrency
#                               rules) — stdlib-only.
#                               Exit 2 (a checker emitting a rule id the
#                               registry doesn't know) is an operational
#                               hard failure, distinct from findings
#   1b. ddplint --protocol      small-scope model check of the declared
#                               rendezvous / router / handoff /
#                               allocator state machines (PL4xx) —
#                               stdlib-only, exhaustive, sub-second.
#                               The fleet/chaos smokes below also replay
#                               their recorded timelines against the
#                               same specs (check_events --conformance)
#   2. ddp_meshsim --check      compile-only scale smoke: cnn + gpt2-small
#                               (dp AND the zero2/zero3 sharded-update
#                               variants) lowered/linted/sized on fake 8-
#                               and 32-device CPU meshes — catches
#                               lowering breaks and SF2xx/SL3xx
#                               regressions at topologies the tests
#                               never build
#   3. check_events --schema-sync
#                               two-way emitter <-> EVENT_KINDS diff, so
#                               a kind added on one side only is a hard
#                               error in BOTH directions
#   4. ddplint --modes serve    inference-engine graph lint: the decode
#                               program must carry NO training
#                               collectives and must keep its KV-pool
#                               donation (GL003) — a lost pool alias
#                               doubles serving memory
#   5. ddp_serve --smoke        end-to-end serving smoke on a tiny
#                               model under a deterministic virtual
#                               clock, two phases: (a) plain engine —
#                               >=1 request completes and the events
#                               dir yields a schema-valid timeline +
#                               structurally valid Perfetto trace with
#                               the request-lifecycle kinds; (b) fast
#                               path — prefix cache + spec decoding on
#                               a shared-prefix Zipf trace must land
#                               >0 prefix hits and >1 mean accepted
#                               tokens/verify, with prefix_hit /
#                               spec_verify kinds schema-valid
#   5b. ddp_serve --fleet 1:2 --smoke
#                               disaggregated serving fleet: 1 prefill +
#                               2 decode engine PROCESSES behind the
#                               session-affinity router, KV-block
#                               handoff over TCP, one decode worker
#                               killed mid-run — asserts every request
#                               completes (zero dropped), >=1 handoff,
#                               >=1 affinity-routed follow-up turn, and
#                               a schema-valid merged timeline with the
#                               route_admit / kv_handoff / tier_summary
#                               / engine_verdict kinds
#   6. elastic shrink smoke     4 -> 3 in-process resize on a fake-device
#                               CPU gang: chaos kills one member mid-run,
#                               the coordinator must land a gang_resize
#                               (NOT a restart_attempt) and finish ok
#   7. integrity smoke          silent bit flip on one rank of a 4-way
#                               CPU gang: the replica digest must detect
#                               it on cadence, the vote must name the
#                               rank, and the gang must EVICT via resize
#                               (sdc_detect + sdc_evict + gang_resize,
#                               no restart_attempt)
#   8. multi-host chaos smoke   3 REAL processes on a TCP rendezvous
#                               store under the supervised launcher,
#                               twice: a host-kill must end on the
#                               resize rung of the degradation ladder
#                               (gang_verdict names the fault, zero
#                               respawns), and a rendezvous-server kill
#                               must re-host the store on the elected
#                               survivor (rdzv_rehost) and still finish
#                               on the resize rung
#   9. ddp_tune --check         autotuner smoke: a real 2-trial search
#                               on a tiny model over an 8-fake-device
#                               CPU mesh — asserts a winner record is
#                               persisted and every tune_* event is
#                               schema-valid
#  10. tier-1 pytest            the ROADMAP verify command (CPU, not
#                               slow).  Includes the ZeRO-2/3 bitwise
#                               dp-parity + low-bit-moment convergence
#                               tests (tests/test_zero23.py)
#
# Opt-in perf regression gate (off by default so tier-1 stays
# deterministic — perf numbers need a quiet, consistent host):
#   DDP_PERF_GATE=1            compare DDP_PERF_GATE_RUN (an events dir,
#                              run_summary JSON, or BENCH_*.json) against
#                              baseline DDP_PERF_GATE_BASELINE (default
#                              "main") in store DDP_PERF_GATE_STORE
#                              (default runs/); non-zero exit on
#                              regression.  Seed a baseline first with
#                              scripts/perf_gate.py ... --update-baseline
#                              BENCH headlines carry z2_hwm_bytes /
#                              z3_hwm_bytes / z2_step_s (the zero2/zero3
#                              per-device live-HWM and step time) — the
#                              *_bytes/*_s suffixes make them
#                              lower-is-better, so a sharded-update
#                              memory regression fails this stage.
#                              integrity_overhead_frac (the --integrity-
#                              every digest's step-time cost, pinned
#                              <= 1%) gates the same way via _frac.
#                              tuned_step_s gates lower-is-better; the
#                              autotuner's tune_gain_frac gates HIGHER-
#                              is-better (gain_frac$ overrides _frac$),
#                              so a shrinking tuning win is a regression
set -euo pipefail
cd "$(dirname "$0")/.."

echo "== ddplint --ast =="
python scripts/ddplint.py --ast

echo "== ddplint --protocol (model-check the declared state machines) =="
python scripts/ddplint.py --protocol

echo "== ddp_meshsim --check =="
python scripts/ddp_meshsim.py --check

echo "== check_events --schema-sync =="
python scripts/check_events.py --schema-sync

echo "== ddplint --graph --modes serve =="
python scripts/ddplint.py --graph --modes serve

echo "== ddp_serve --smoke =="
SERVE_SMOKE_DIR="$(mktemp -d)"
python scripts/ddp_serve.py --smoke --events-dir "${SERVE_SMOKE_DIR}"
rm -rf "${SERVE_SMOKE_DIR}"

echo "== ddp_serve --fleet 1:2 --smoke (disaggregated prefill/decode) =="
FLEET_SMOKE_DIR="$(mktemp -d)"
python scripts/ddp_serve.py --fleet 1:2 --smoke \
    --events-dir "${FLEET_SMOKE_DIR}"
echo "== check_events --conformance (fleet smoke timeline) =="
python scripts/check_events.py --conformance "${FLEET_SMOKE_DIR}"
echo "== check_events --lineage (span trees across process boundaries) =="
python scripts/check_events.py --lineage "${FLEET_SMOKE_DIR}"
rm -rf "${FLEET_SMOKE_DIR}"

echo "== elastic shrink smoke (4 -> 3) =="
ELASTIC_SMOKE_DIR="$(mktemp -d)"
JAX_PLATFORMS=cpu python dpp.py --model mlp --fake-devices 4 \
    --batch-size 4 --epochs 1 --steps-per-epoch 8 \
    --elastic --chaos "worker-kill@3:2" \
    --events-dir "${ELASTIC_SMOKE_DIR}"
python - "${ELASTIC_SMOKE_DIR}" <<'PY'
import sys
from distributeddataparallel_tpu.observability.events import load_timeline
kinds = [r.get("kind") for r in load_timeline(sys.argv[1])]
resizes = kinds.count("gang_resize")
assert resizes == 1, f"expected exactly 1 gang_resize, saw {resizes}"
assert "restart_attempt" not in kinds, \
    "elastic shrink fell back to a supervised restart"
print(f"elastic shrink smoke: 1 gang_resize, 0 restarts "
      f"({len(kinds)} records)")
PY
rm -rf "${ELASTIC_SMOKE_DIR}"

echo "== integrity smoke (bitflip -> detect -> evict) =="
INTEGRITY_SMOKE_DIR="$(mktemp -d)"
JAX_PLATFORMS=cpu python dpp.py --model mlp --fake-devices 4 \
    --batch-size 4 --epochs 1 --steps-per-epoch 8 \
    --elastic --integrity-every 2 --chaos "bitflip@4:1" \
    --events-dir "${INTEGRITY_SMOKE_DIR}"
python - "${INTEGRITY_SMOKE_DIR}" <<'PY'
import sys
from distributeddataparallel_tpu.observability.events import load_timeline
recs = load_timeline(sys.argv[1])
kinds = [r.get("kind") for r in recs]
detect = next((r for r in recs if r.get("kind") == "sdc_detect"), None)
assert detect is not None, f"no sdc_detect in {sorted(set(kinds))}"
assert detect["rank"] == 1, f"vote named rank {detect['rank']}, not 1"
evict = next((r for r in recs if r.get("kind") == "sdc_evict"), None)
assert evict is not None and evict["rank"] == 1, evict
assert kinds.count("gang_resize") == 1, kinds
assert "restart_attempt" not in kinds, \
    "SDC eviction fell back to a supervised restart"
print(f"integrity smoke: sdc_detect rank 1 -> evict -> 1 gang_resize, "
      f"0 restarts ({len(kinds)} records)")
PY
rm -rf "${INTEGRITY_SMOKE_DIR}"

echo "== multi-host chaos smoke (host-kill -> resize; rdzv-kill -> re-host) =="
HOSTGANG_SMOKE_DIR="$(mktemp -d)"
JAX_PLATFORMS=cpu python scripts/chaos_smoke.py "${HOSTGANG_SMOKE_DIR}"
echo "== check_events --conformance (chaos smoke timeline) =="
python scripts/check_events.py --conformance "${HOSTGANG_SMOKE_DIR}"
rm -rf "${HOSTGANG_SMOKE_DIR}"

echo "== ddp_tune --check =="
python scripts/ddp_tune.py --check

if [[ "${DDP_PERF_GATE:-0}" == "1" ]]; then
    echo "== perf_gate =="
    : "${DDP_PERF_GATE_RUN:?DDP_PERF_GATE=1 needs DDP_PERF_GATE_RUN}"
    python scripts/perf_gate.py "${DDP_PERF_GATE_RUN}" \
        --store "${DDP_PERF_GATE_STORE:-runs}" \
        --baseline "${DDP_PERF_GATE_BASELINE:-main}"
fi

if [[ "${1:-}" == "--fast" ]]; then
    echo "ci.sh --fast: static gates clean; skipping pytest tier"
    exit 0
fi

echo "== tier-1 pytest =="
timeout -k 10 870 env JAX_PLATFORMS=cpu \
    python -m pytest tests/ -q -m 'not slow' \
    --continue-on-collection-errors \
    -p no:cacheprovider -p no:xdist -p no:randomly

echo "ci.sh: all gates clean"
